"""PS RPC plane: threaded socket server + sharded client + async Communicator.

Reference parity: `ps/service/brpc_ps_client.h` / `brpc_ps_server.cc`
(pull/push dense+sparse RPCs), `ps/service/communicator/communicator.cc:1`
(async grad send batching), proto `sendrecv.proto`.

Redesign: brpc is replaced by a length-prefixed binary protocol over raw
sockets (the C++ TCPStore's wire style) — request header `cmd table n dim`
+ raw little-endian buffers, no pickle on the hot path. Every response
starts with a one-byte status; errors carry a message frame so server-side
failures (unknown table, barrier timeout) surface to the caller instead of
tearing the connection down. Sparse tables shard across servers by
`id % n_servers`; dense tables are row-range sharded across all
servers. Shard RPCs are issued
send-first-then-receive so a pull touches all servers in ~one RTT (the
brpc client's concurrent-request role).
"""
from __future__ import annotations

import os
import random
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .table import DenseTable, SparseTable
from ... import faults as _faults
from ... import monitor as _monitor
from ...core import flags as _flags
from ...obs import trace as _trace

_HDR = struct.Struct("<B16sqq")  # cmd, table name (padded), n, dim
# payload plausibility caps (the header fields are client-controlled)
_MAX_PAYLOAD_ROWS = 1 << 24      # 16M ids per request
_MAX_PAYLOAD_DIM = 1 << 16       # 64K embedding width
_MAX_PAYLOAD_ELEMS = 1 << 28     # 256M f32 elems ≈ 1 GiB
_LEN = struct.Struct("<q")
CMD_PULL_SPARSE = 1
CMD_PUSH_SPARSE = 2
CMD_PULL_DENSE = 3
CMD_PUSH_DENSE = 4
CMD_STOP = 5
CMD_BARRIER = 6
CMD_PUSH_SHOW_CLICK = 7
CMD_DECAY = 8
CMD_SHRINK = 9
CMD_ADD_SPARSE = 10      # table-config negotiation (optimizer + accessor)
CMD_ADD_DENSE = 11
CMD_SAMPLE_NEIGHBORS = 12   # graph table: ids[n] -> [n, k] ids + weights
CMD_NODE_FEAT = 13          # graph table: ids[n] -> [n, feat_dim] f32
# Resilience extension (python plane). HELLO registers the client id for
# this connection — the id rides the header's NAME field (no payload), so
# a server that predates it (the native csrc/ps_server.cpp plane) answers
# with a plain unknown-cmd error frame and the stream stays in sync; the
# client then marks the endpoint legacy and keeps using unsequenced
# pushes. Sequenced pushes prefix their payload with an i64 request seq;
# the server applies each (client, seq) AT MOST ONCE, so a push retried
# after a lost ACK cannot double-apply the gradient.
CMD_HELLO = 14              # client id in the name field, no payload
CMD_PUSH_SPARSE_SEQ = 15    # i64 seq + CMD_PUSH_SPARSE payload
CMD_PUSH_DENSE_SEQ = 16     # i64 seq + CMD_PUSH_DENSE payload

from .table import OPT_WIRE_IDS as _OPT_IDS  # single source, both planes
_SPARSE_CFG = struct.Struct("<ffqBBfffffff")   # lr,std,seed,opt,ctr,b1,b2,eps,sdec,ccoef,dth,ttl
_DENSE_CFG = struct.Struct("<fqqBfff")          # lr,shard_lo,total,opt,b1,b2,eps
_ST_OK = b"\x01"
_ST_ERR = b"\x00"

_BARRIER_TIMEOUT = 60.0


class PsError(RuntimeError):
    """Server-reported request failure (carried in an error frame)."""


from ...utils.net import recv_exact as _recv_exact  # noqa: E402


def _tname(name: str) -> bytes:
    b = name.encode()
    if len(b) > 16:
        raise ValueError(
            f"ps table name {name!r} exceeds the 16-byte wire limit")
    return b.ljust(16, b"\0")


def _send_err(conn, msg: str):
    m = msg.encode()
    conn.sendall(_ST_ERR + _LEN.pack(len(m)) + m)


def _check_status(sock, deadline: Optional[float] = None):
    """Read the response status byte; raise PsError on an error frame.
    `deadline` (absolute monotonic) bounds the wait on a stalled peer."""
    st = _recv_exact(sock, 1, deadline)
    if st == _ST_OK:
        return
    (ln,) = _LEN.unpack(_recv_exact(sock, 8, deadline))
    raise PsError(_recv_exact(sock, ln, deadline).decode())


class PsServer:
    """One parameter-server process/thread (brpc_ps_server role)."""

    def __init__(self, host="127.0.0.1", port=0):
        self._tables: Dict[str, object] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # generation-counted barrier: CMD_BARRIER carries n participants;
        # the ACK is held until all n arrive (gloo-barrier role)
        self._barrier_cond = threading.Condition()
        self._barrier_arrived = 0
        self._barrier_gen = 0
        # at-most-once push ledger: client id -> last applied request seq
        # (survives the client's reconnects — that is the point)
        self._applied_seq: Dict[str, int] = {}
        self._seq_lock = threading.Lock()

    def add_sparse_table(self, name, dim, **kw):
        _tname(name)  # validate against the wire limit at registration
        self._tables[name] = SparseTable(dim, **kw)
        return self._tables[name]

    def add_dense_table(self, name, shape, **kw):
        _tname(name)
        self._tables[name] = DenseTable(shape, **kw)
        return self._tables[name]

    def add_graph_table(self, name, **kw):
        from .graph_table import GraphTable
        _tname(name)
        self._tables[name] = GraphTable(**kw)
        return self._tables[name]

    def table(self, name):
        return self._tables[name]

    def run(self, block=False):
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        if block:
            self._thread.join()
        return self

    def _serve(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _barrier(self, n_participants: int):
        with self._barrier_cond:
            gen = self._barrier_gen
            self._barrier_arrived += 1
            if self._barrier_arrived >= max(n_participants, 1):
                self._barrier_arrived = 0
                self._barrier_gen += 1
                self._barrier_cond.notify_all()
                return
            if not self._barrier_cond.wait_for(
                    lambda: self._barrier_gen != gen,
                    timeout=_BARRIER_TIMEOUT):
                # roll back our arrival so later generations aren't corrupted
                if self._barrier_gen == gen:
                    self._barrier_arrived -= 1
                raise PsError(
                    f"barrier timed out after {_BARRIER_TIMEOUT}s "
                    f"({n_participants} participants expected)")

    def _handle(self, conn):
        client_id: Optional[str] = None   # set by CMD_HELLO, per connection
        try:
            while True:
                hdr = _recv_exact(conn, _HDR.size)
                cmd, name, n, dim = _HDR.unpack(hdr)
                name = name.rstrip(b"\0").decode()
                if _faults._ENABLED:
                    # injected conn_reset lands in the outer except and
                    # drops this handler's connection — the server stays
                    # up, the client reconnects and retries
                    _faults.check("ps.server")
                # bound the (client-controlled) payload size before any
                # allocation: a corrupt/hostile header must produce an
                # error frame + connection drop, not a multi-GB buffer or
                # a dead handler thread
                if not (0 <= n <= _MAX_PAYLOAD_ROWS
                        and 0 <= dim <= _MAX_PAYLOAD_DIM
                        and n * max(dim, 1) <= _MAX_PAYLOAD_ELEMS):
                    _send_err(conn, f"ps: implausible header n={n} "
                                    f"dim={dim}")
                    return
                # read the FULL request payload before processing so an
                # error reply leaves the stream in sync for the next request
                ids = grads = None
                req_seq = None
                if cmd == CMD_PUSH_SPARSE_SEQ:
                    (req_seq,) = _LEN.unpack(_recv_exact(conn, 8))
                    cmd = CMD_PUSH_SPARSE
                elif cmd == CMD_PUSH_DENSE_SEQ:
                    (req_seq,) = _LEN.unpack(_recv_exact(conn, 8))
                    cmd = CMD_PUSH_DENSE
                if cmd == CMD_PULL_SPARSE:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                elif cmd == CMD_PUSH_SPARSE:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                    grads = np.frombuffer(
                        _recv_exact(conn, 4 * n * dim), np.float32
                    ).reshape(n, dim)
                elif cmd == CMD_PUSH_DENSE:
                    grads = np.frombuffer(_recv_exact(conn, 4 * n), np.float32)
                elif cmd == CMD_PUSH_SHOW_CLICK:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                    grads = np.frombuffer(
                        _recv_exact(conn, 4 * n * 2), np.float32)
                elif cmd == CMD_ADD_SPARSE:
                    cfg_raw = _recv_exact(conn, _SPARSE_CFG.size)
                elif cmd == CMD_ADD_DENSE:
                    cfg_raw = _recv_exact(conn, _DENSE_CFG.size)
                elif cmd in (CMD_SAMPLE_NEIGHBORS, CMD_NODE_FEAT):
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                try:
                    if cmd == CMD_STOP:
                        conn.sendall(_ST_OK)
                        self._stop.set()
                        return
                    if cmd == CMD_BARRIER:
                        self._barrier(int(n))
                        conn.sendall(_ST_OK)
                        continue
                    if cmd == CMD_HELLO:
                        client_id = name
                        conn.sendall(_ST_OK)
                        continue
                    if req_seq is not None:
                        if client_id is None:
                            raise PsError(
                                "ps: sequenced push before CMD_HELLO")
                        with self._seq_lock:
                            duplicate = req_seq <= self._applied_seq.get(
                                client_id, 0)
                            if not duplicate:
                                self._applied_seq[client_id] = req_seq
                        if duplicate:
                            # a retry of an already-applied push: ACK
                            # without touching the table (at-most-once)
                            conn.sendall(_ST_OK)
                            continue
                    if cmd == CMD_ADD_SPARSE:
                        (lr, istd, seed, opt, ctr, b1, b2, eps, sdec, ccoef,
                         dth, ttl) = _SPARSE_CFG.unpack(cfg_raw)
                        if name in self._tables:
                            raise ValueError(
                                f"ps: table {name!r} already registered")
                        opt_name = {0: "sgd", 1: "adagrad", 2: "adam"}[opt]
                        kw = {}
                        if ctr:
                            kw = dict(accessor="ctr", show_decay_rate=sdec,
                                      click_coeff=ccoef,
                                      delete_threshold=dth, ttl_days=ttl)
                        self.add_sparse_table(
                            name, int(dim), optimizer=opt_name, lr=lr,
                            init_std=istd, seed=int(seed), beta1=b1,
                            beta2=b2, eps=eps, **kw)
                        conn.sendall(_ST_OK)
                        continue
                    if cmd == CMD_ADD_DENSE:
                        lr, lo, total, opt, b1, b2, eps = \
                            _DENSE_CFG.unpack(cfg_raw)
                        if name in self._tables:
                            raise ValueError(
                                f"ps: table {name!r} already registered")
                        opt_name = {0: "sgd", 1: "adagrad", 2: "adam"}[opt]
                        self.add_dense_table(name, (int(n),),
                                             optimizer=opt_name, lr=lr,
                                             beta1=b1, beta2=b2, eps=eps,
                                             shard_lo=int(lo),
                                             total_size=int(total) if
                                             total > 0 else int(n))
                        conn.sendall(_ST_OK)
                        continue
                    tbl = self._tables.get(name)
                    if tbl is None:
                        raise KeyError(f"ps: unknown table {name!r}")
                    if cmd == CMD_PULL_SPARSE:
                        rows = tbl.pull(ids)
                        conn.sendall(_ST_OK + rows.astype(np.float32).tobytes())
                    elif cmd == CMD_PUSH_SPARSE:
                        tbl.push(ids, grads)
                        conn.sendall(_ST_OK)
                    elif cmd == CMD_PULL_DENSE:
                        w = tbl.pull().astype(np.float32)
                        lo, _hi = getattr(tbl, "shard_range", (0, w.size))
                        total = getattr(tbl, "total_size", w.size)
                        # slice + (offset, total) so the client can verify
                        # the shards tile exactly one table
                        conn.sendall(_ST_OK + _LEN.pack(w.size)
                                     + _LEN.pack(lo) + _LEN.pack(total)
                                     + w.tobytes())
                    elif cmd == CMD_PUSH_DENSE:
                        tbl.push(grads.reshape(tbl.w.shape))
                        conn.sendall(_ST_OK)
                    elif cmd == CMD_PUSH_SHOW_CLICK:
                        tbl.push_show_click(ids, grads[:n], grads[n:])
                        conn.sendall(_ST_OK)
                    elif cmd == CMD_DECAY:
                        tbl.decay()
                        conn.sendall(_ST_OK)
                    elif cmd == CMD_SHRINK:
                        evicted = tbl.shrink()
                        conn.sendall(_ST_OK + _LEN.pack(int(evicted)))
                    elif cmd == CMD_SAMPLE_NEIGHBORS:
                        nb, w = tbl.sample_neighbors(ids, int(dim))
                        conn.sendall(_ST_OK + nb.astype(np.int64).tobytes()
                                     + w.astype(np.float32).tobytes())
                    elif cmd == CMD_NODE_FEAT:
                        f = tbl.get_node_feat(ids).astype(np.float32)
                        conn.sendall(_ST_OK + _LEN.pack(f.shape[1])
                                     + f.tobytes())
                    else:
                        raise ValueError(f"ps: unknown command {cmd}")
                except (KeyError, ValueError, PsError, AttributeError,
                        TypeError) as e:
                    # AttributeError/TypeError: a table-op aimed at a table
                    # type without that surface (e.g. DECAY on a dense
                    # table) must produce a protocol error frame — the C++
                    # server answers the same request with one
                    _send_err(conn, str(e))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=2)


_CLIENT_SEQ = [0]
_CLIENT_SEQ_LOCK = threading.Lock()


def _new_client_id() -> bytes:
    """16-byte wire client id, unique across processes and instances
    (pid + in-process counter, hex — fits the header's name field)."""
    with _CLIENT_SEQ_LOCK:
        _CLIENT_SEQ[0] += 1
        n = _CLIENT_SEQ[0]
    return f"{os.getpid() % 0xFFFF:04x}{n % 0xFFFF:04x}" \
        f"{random.getrandbits(32):08x}".encode()


class PsClient:
    """Sharded client (brpc_ps_client role): sparse ids route to server
    `id % n_servers`; dense tables are row-range sharded across all
    servers (pull concatenates, push scatters).

    Self-healing transport: a transport error invalidates the cached
    connection, and every data-plane RPC is retried with exponential
    backoff + jitter up to `max_retries` times, reconnecting
    transparently (`ps.retries` / `ps.reconnects` monitor counters).
    Pulls are idempotent and retried freely; pushes carry a per-client
    request sequence (CMD_HELLO capability handshake per connection) so a
    push retried after a lost ACK is applied AT MOST ONCE server-side.
    Endpoints that reject CMD_HELLO (the native C++ plane) are marked
    legacy and keep plain at-least-once pushes. `call_timeout` bounds
    connect and each response read, so a stalled-but-open server raises
    TimeoutError (feeding the retry loop) instead of hanging the caller.
    """

    def __init__(self, endpoints: Sequence[str],
                 max_retries: Optional[int] = None,
                 backoff_ms: Optional[float] = None,
                 call_timeout: Optional[float] = None):
        self.endpoints = list(endpoints)
        self.max_retries = int(_flags.flag("ps_rpc_max_retries")
                               if max_retries is None else max_retries)
        self.backoff_s = float(_flags.flag("ps_rpc_backoff_ms")
                               if backoff_ms is None else backoff_ms) / 1e3
        ct = float(_flags.flag("ps_rpc_call_timeout_s")
                   if call_timeout is None else call_timeout)
        self.call_timeout = ct if ct > 0 else None
        self._socks: List[Optional[socket.socket]] = [None] * len(endpoints)
        self._locks = [threading.Lock() for _ in endpoints]
        self._dims: Dict[str, int] = {}  # table -> row dim (accessor config)
        self._dense_sizes: Dict[str, list] = {}  # table -> per-server sizes
        self._client_id = _new_client_id()
        self._push_seq = [0] * len(endpoints)   # per-server request seq
        self._connected_once = [False] * len(endpoints)
        # per-CONNECTION hello state (None = not negotiated yet) and the
        # per-ENDPOINT legacy verdict (sticky: a native server stays one)
        self._hello_ok: List[Optional[bool]] = [None] * len(endpoints)
        self._legacy = [False] * len(endpoints)

    def _sock(self, i):
        if self._socks[i] is None:
            host, port = self.endpoints[i].rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=self.call_timeout or 120)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._connected_once[i]:
                if _monitor._ENABLED:
                    _monitor.count("ps.reconnects")
            self._connected_once[i] = True
            self._socks[i] = s
        return self._socks[i]

    def _drop(self, i):
        # a transport error leaves the stream byte-desynced: close and
        # forget the socket so the next request starts clean
        if self._socks[i] is not None:
            try:
                self._socks[i].close()
            except OSError:
                pass
            self._socks[i] = None
        self._hello_ok[i] = None   # renegotiate on the next connection

    def _deadline(self) -> Optional[float]:
        return (time.monotonic() + self.call_timeout
                if self.call_timeout else None)

    def _retry_rpc(self, attempt_fn, op: str = "call"):
        """Run one RPC attempt; on a transport failure (OSError family —
        includes injected resets and recv deadlines) back off and retry.
        Server-reported PsErrors are application failures: never retried.
        Caller must already hold the involved per-server locks so a
        retried push reuses its sequence numbers without interleaving.

        Under `FLAGS_trace` the WHOLE call (retries included) is one
        `ps.rpc.<op>` span — parented on the calling thread's open span
        when there is one — that closes with error status when the RPC
        ultimately fails (injected `ps.rpc.send` conn-resets/timeouts
        land here: no leaked open spans)."""
        sp = _trace.span(f"ps.rpc.{op}")
        delay = self.backoff_s
        last: Optional[BaseException] = None
        try:
            for attempt in range(self.max_retries + 1):
                if attempt:
                    if _monitor._ENABLED:
                        _monitor.count("ps.retries")
                    time.sleep(delay * (1.0 + random.random()))  # full jitter
                    delay = min(delay * 2, 2.0)
                try:
                    out = attempt_fn()
                    sp.end(retries=attempt)
                    return out
                except PsError:
                    raise
                except OSError as e:
                    last = e
            raise last
        except BaseException as e:
            # idempotent: only fires when the success path did not end it
            sp.end(status=_trace.STATUS_ERROR,
                   error=f"{type(e).__name__}: {str(e)[:200]}")
            raise

    def _ensure_seq(self, s: int) -> bool:
        """True when the CURRENT connection to server s has a registered
        client id (sequenced pushes allowed). One HELLO per connection;
        an error frame marks the endpoint legacy for good."""
        if self._legacy[s]:
            return False
        sk = self._sock(s)
        if self._hello_ok[s] is not None:
            return self._hello_ok[s]
        try:
            sk.sendall(_HDR.pack(CMD_HELLO, self._client_id, 0, 0))
            _check_status(sk, self._deadline())
            self._hello_ok[s] = True
        except PsError:
            self._legacy[s] = True
            self._hello_ok[s] = False
        except OSError:
            self._drop(s)
            raise
        return self._hello_ok[s]

    def _next_push_seq(self, s: int) -> int:
        self._push_seq[s] += 1
        return self._push_seq[s]

    def _shard_sel(self, ids):
        n_srv = len(self.endpoints)
        m = ids % n_srv  # one modulo pass over the id vector
        out = []
        for s in range(n_srv):
            sel = np.where(m == s)[0]
            if len(sel):
                out.append((s, sel))
        return out

    def _send_all(self, shards, make_payload):
        """Send one request per shard; on a transport error every involved
        socket is dropped (earlier sends may have unread responses that
        would byte-desync a reused connection)."""
        try:
            for s, sel in shards:
                if _faults._ENABLED:
                    _faults.check("ps.rpc.send")
                self._sock(s).sendall(make_payload(s, sel))
        except OSError:
            for s, _ in shards:
                self._drop(s)
            raise

    def _recv_all(self, shards, recv_one, deadline: Optional[float] = None):
        """Read every shard's response even if one errors (keeps the other
        sockets in sync); re-raise the first failure afterwards."""
        first: Optional[BaseException] = None
        for s, sel in shards:
            sk = self._socks[s]
            if sk is None:
                continue
            try:
                if _faults._ENABLED:
                    _faults.check("ps.rpc.recv")
                _check_status(sk, deadline)
                if recv_one is not None:
                    recv_one(s, sel, sk)
            except OSError as e:
                self._drop(s)
                first = first or e
            except PsError as e:
                first = first or e
        if first is not None:
            raise first

    # -- sparse --
    def register_sparse_dim(self, table: str, dim: int):
        """Client-side table metadata (the reference ships this in the
        TableAccessor config)."""
        self._dims[table] = dim

    def pull_sparse(self, table: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        dim = self._dims[table]
        shards = self._shard_sel(ids)
        out = np.empty((len(ids), dim), np.float32)
        # acquire in ascending shard order (deadlock-free), send all
        # requests, then collect all responses: ~one RTT total
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            def attempt():
                deadline = self._deadline()
                self._send_all(shards, lambda s, sel: (
                    _HDR.pack(CMD_PULL_SPARSE, _tname(table), len(sel), 0)
                    + ids[sel].tobytes()))

                def recv_rows(s, sel, sk):
                    out[sel] = np.frombuffer(
                        _recv_exact(sk, 4 * len(sel) * dim, deadline),
                        np.float32).reshape(len(sel), dim)

                self._recv_all(shards, recv_rows, deadline)

            self._retry_rpc(attempt, op="pull_sparse")
        finally:
            for s, _ in shards:
                self._locks[s].release()
        return out

    def push_sparse(self, table: str, ids, grads):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        shards = self._shard_sel(ids)
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            # one seq per involved server for the WHOLE call: every retry
            # resends the same seq, so the server applies it at most once
            seqs = {s: self._next_push_seq(s) for s, _ in shards}

            def attempt():
                deadline = self._deadline()

                def payload(s, sel):
                    g = grads[sel]  # one fancy-index copy per shard
                    if self._ensure_seq(s):
                        return (_HDR.pack(CMD_PUSH_SPARSE_SEQ, _tname(table),
                                          len(sel), g.shape[1])
                                + _LEN.pack(seqs[s])
                                + ids[sel].tobytes() + g.tobytes())
                    return (_HDR.pack(CMD_PUSH_SPARSE, _tname(table),
                                      len(sel), g.shape[1])
                            + ids[sel].tobytes() + g.tobytes())

                self._send_all(shards, payload)
                self._recv_all(shards, None, deadline)

            self._retry_rpc(attempt, op="push_sparse")
        finally:
            for s, _ in shards:
                self._locks[s].release()

    # -- dense --
    # Dense tables are row-range sharded across ALL servers (reference
    # `common_dense_table.cc`): pull fans one request per server and
    # concatenates the slices; push scatters the grad by the same ranges.
    # Slice sizes are learned on the first pull (each response carries its
    # size) and cached for pushes.

    def pull_dense(self, table: str) -> np.ndarray:
        n_srv = len(self.endpoints)
        shards = [(s, None) for s in range(n_srv)]
        parts: list = [None] * n_srv
        metas: list = [None] * n_srv
        for s, _ in shards:
            self._locks[s].acquire()
        try:
            def attempt():
                deadline = self._deadline()
                self._send_all(shards, lambda s, sel: _HDR.pack(
                    CMD_PULL_DENSE, _tname(table), 0, 0))

                def recv_slice(s, sel, sk):
                    (size,) = _LEN.unpack(_recv_exact(sk, 8, deadline))
                    (lo,) = _LEN.unpack(_recv_exact(sk, 8, deadline))
                    (total,) = _LEN.unpack(_recv_exact(sk, 8, deadline))
                    metas[s] = (lo, size, total)
                    parts[s] = np.frombuffer(
                        _recv_exact(sk, 4 * size, deadline),
                        np.float32).copy()

                self._recv_all(shards, recv_slice, deadline)

            self._retry_rpc(attempt, op="pull_dense")
        finally:
            for s, _ in shards:
                self._locks[s].release()
        # the per-server slices must tile [0, total) exactly — this catches
        # tables registered unsharded on several servers (duplicate full
        # copies) or with inconsistent shard specs
        total = metas[0][2]
        ordered = sorted(range(n_srv), key=lambda s: metas[s][0])
        cursor = 0
        for s in ordered:
            lo, size, tot = metas[s]
            if tot != total or lo != cursor:
                raise PsError(
                    f"pull_dense('{table}'): server shards do not tile the "
                    f"table (server {s} reports offset {lo} size {size} "
                    f"total {tot}; expected offset {cursor} total {total}) "
                    "— register with shard=(i, n_servers) on every server")
            cursor += size
        if cursor != total:
            raise PsError(
                f"pull_dense('{table}'): shards cover {cursor} of {total} "
                "elements")
        self._dense_sizes[table] = [(metas[s][0], metas[s][1])
                                    for s in range(n_srv)]
        return np.concatenate([parts[s] for s in ordered])

    def push_dense(self, table: str, grad):
        g = np.asarray(grad, np.float32).reshape(-1)
        ranges = self._dense_sizes.get(table)
        if ranges is None:
            self.pull_dense(table)  # learn (and validate) the shard split
            ranges = self._dense_sizes[table]
        total = sum(size for _, size in ranges)
        if total != g.size:
            raise PsError(
                f"push_dense('{table}'): grad size {g.size} != table size "
                f"{total}")
        shards = [(s, (lo, lo + size))
                  for s, (lo, size) in enumerate(ranges) if size]
        for s, _ in shards:
            self._locks[s].acquire()
        try:
            seqs = {s: self._next_push_seq(s) for s, _ in shards}

            def attempt():
                deadline = self._deadline()

                def payload(s, sel):
                    body = g[sel[0]:sel[1]].tobytes()
                    if self._ensure_seq(s):
                        return (_HDR.pack(CMD_PUSH_DENSE_SEQ, _tname(table),
                                          sel[1] - sel[0], 0)
                                + _LEN.pack(seqs[s]) + body)
                    return (_HDR.pack(CMD_PUSH_DENSE, _tname(table),
                                      sel[1] - sel[0], 0) + body)

                self._send_all(shards, payload)
                self._recv_all(shards, None, deadline)

            self._retry_rpc(attempt, op="push_dense")
        finally:
            for s, _ in shards:
                self._locks[s].release()

    # -- CTR accessor ops (ctr_accessor.cc role over the wire) --
    def push_show_click(self, table: str, ids, shows, clicks):
        """Bump per-row show/click statistics on the owning servers."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        shows = np.asarray(shows, np.float32).reshape(-1)
        clicks = np.asarray(clicks, np.float32).reshape(-1)
        shards = self._shard_sel(ids)
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            def attempt():
                deadline = self._deadline()
                self._send_all(shards, lambda s, sel: (
                    _HDR.pack(CMD_PUSH_SHOW_CLICK, _tname(table), len(sel), 0)
                    + ids[sel].tobytes() + shows[sel].tobytes()
                    + clicks[sel].tobytes()))
                self._recv_all(shards, None, deadline)

            self._retry_rpc(attempt, op="push_show_click")
        finally:
            for s, _ in shards:
                self._locks[s].release()

    def _simple_cmd_all(self, cmd, table, recv_extra=None):
        """Fire `cmd` at every server; returns the per-server extras."""
        shards = [(i, None) for i in range(len(self.endpoints))]
        outs = [None] * len(self.endpoints)
        for s, _ in shards:
            self._locks[s].acquire()
        try:
            def attempt():
                deadline = self._deadline()
                self._send_all(shards, lambda s, sel: _HDR.pack(
                    cmd, _tname(table), 0, 0))

                def recv_one(s, sel, sk):
                    if recv_extra is not None:
                        outs[s] = recv_extra(sk)

                self._recv_all(shards, recv_one, deadline)

            self._retry_rpc(attempt, op="cmd")
        finally:
            for s, _ in shards:
                self._locks[s].release()
        return outs

    def decay(self, table: str):
        """One show/click time-decay cycle on every server."""
        self._simple_cmd_all(CMD_DECAY, table)

    def shrink(self, table: str) -> int:
        """Evict low-score/expired rows everywhere; total evicted."""
        outs = self._simple_cmd_all(
            CMD_SHRINK, table,
            recv_extra=lambda sk: _LEN.unpack(_recv_exact(sk, 8))[0])
        return int(np.sum([o or 0 for o in outs]))

    # -- table-config negotiation (the reference ships TableAccessor
    #    configs to every server at fleet init; these do it per table) --
    def create_sparse_table(self, table: str, dim: int, optimizer="sgd",
                            lr=0.01, init_std=0.01, seed=0, accessor=None,
                            show_decay_rate=0.98, click_coeff=8.0,
                            delete_threshold=0.8, ttl_days=30.0,
                            beta1=0.9, beta2=0.999, eps=1e-8):
        cfg = _SPARSE_CFG.pack(
            lr, init_std, int(seed), _OPT_IDS[optimizer],
            1 if accessor == "ctr" else 0, beta1, beta2, eps,
            show_decay_rate, click_coeff, delete_threshold, float(ttl_days))
        shards = [(i, None) for i in range(len(self.endpoints))]
        for s, _ in shards:
            self._locks[s].acquire()
        try:
            self._send_all(shards, lambda s, sel: _HDR.pack(
                CMD_ADD_SPARSE, _tname(table), 0, dim) + cfg)
            self._recv_all(shards, None)
        finally:
            for s, _ in shards:
                self._locks[s].release()
        self.register_sparse_dim(table, dim)

    def create_dense_table(self, table: str, total: int, optimizer="sgd",
                           lr=0.01, beta1=0.9, beta2=0.999, eps=1e-8):
        from .table import dense_shard_range
        n_srv = len(self.endpoints)
        for i in range(n_srv):
            lo, hi = dense_shard_range(int(total), i, n_srv)
            cfg = _DENSE_CFG.pack(lr, lo, int(total), _OPT_IDS[optimizer],
                                  beta1, beta2, eps)
            with self._locks[i]:
                sk = self._sock(i)
                sk.sendall(_HDR.pack(CMD_ADD_DENSE, _tname(table), hi - lo, 0)
                           + cfg)
                _check_status(sk)

    # -- graph table (common_graph_table.h role) --
    def sample_neighbors(self, table: str, ids, k: int):
        """[n] node ids -> ([n, k] neighbor ids, [n, k] weights); nodes
        route to their owning server (id % n_servers, like sparse rows)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        shards = self._shard_sel(ids)
        nb = np.full((len(ids), k), -1, np.int64)
        w = np.zeros((len(ids), k), np.float32)
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            self._send_all(shards, lambda s, sel: (
                _HDR.pack(CMD_SAMPLE_NEIGHBORS, _tname(table), len(sel), k)
                + ids[sel].tobytes()))

            def recv_one(s, sel, sk):
                nb[sel] = np.frombuffer(
                    _recv_exact(sk, 8 * len(sel) * k), np.int64
                ).reshape(len(sel), k)
                w[sel] = np.frombuffer(
                    _recv_exact(sk, 4 * len(sel) * k), np.float32
                ).reshape(len(sel), k)

            self._recv_all(shards, recv_one)
        finally:
            for s, _ in shards:
                self._locks[s].release()
        return nb, w

    def node_feat(self, table: str, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        shards = self._shard_sel(ids)
        parts = {}
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            self._send_all(shards, lambda s, sel: (
                _HDR.pack(CMD_NODE_FEAT, _tname(table), len(sel), 0)
                + ids[sel].tobytes()))

            def recv_one(s, sel, sk):
                (d,) = _LEN.unpack(_recv_exact(sk, 8))
                parts[s] = (sel, np.frombuffer(
                    _recv_exact(sk, 4 * len(sel) * d), np.float32
                ).reshape(len(sel), d))

            self._recv_all(shards, recv_one)
        finally:
            for s, _ in shards:
                self._locks[s].release()
        d = max(p.shape[1] for _, p in parts.values())
        out = np.zeros((len(ids), d), np.float32)
        for sel, p in parts.values():
            out[sel, :p.shape[1]] = p
        return out

    def barrier(self, n_trainers: int = 1):
        """Block until `n_trainers` clients reach this point (coordinated by
        server 0 — the gloo-barrier role in the reference's PS bring-up)."""
        with self._locks[0]:
            try:
                sk = self._sock(0)
                sk.sendall(_HDR.pack(CMD_BARRIER, _tname(""), n_trainers, 0))
                # the ACK is legitimately held until all trainers arrive;
                # bound the wait by the server's own barrier timeout
                _check_status(sk, time.monotonic() + _BARRIER_TIMEOUT + 30)
            except OSError:
                self._drop(0)
                raise

    def stop_server(self):
        for s in range(len(self.endpoints)):
            try:
                with self._locks[s]:
                    sk = self._sock(s)
                    sk.sendall(_HDR.pack(CMD_STOP, _tname(""), 0, 0))
                    _check_status(sk)
            except (ConnectionError, OSError, PsError):
                pass

    def close(self):
        for i in range(len(self._socks)):
            self._drop(i)


class Communicator:
    """Async grad sender (communicator.cc role): push_sparse calls are
    queued and flushed by a background thread, overlapping server updates
    with the trainer's next step; `flush()`/`barrier()` give the sync
    points the reference exposes."""

    def __init__(self, client: PsClient, max_queue=64):
        self.client = client
        import queue as q
        self._q = q.Queue(maxsize=max_queue)
        # pending counts enqueued-but-not-yet-applied items; a Condition
        # (not q.empty + idle flag) closes the pop-before-clear race where
        # flush() could return while the last push was still in flight
        self._pending = 0
        self._cond = threading.Condition()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            kind, table, a, b = item
            try:
                if self._error is None:
                    if kind == "sparse":
                        self.client.push_sparse(table, a, b)
                    else:
                        self.client.push_dense(table, a)
            except BaseException as e:  # surface on next flush/push
                self._error = e
            finally:
                with self._cond:
                    self._pending -= 1
                    if self._pending == 0:
                        self._cond.notify_all()

    def _raise_if_failed(self):
        if self._error is not None:
            raise RuntimeError(
                "Communicator push failed; queued gradients were dropped"
            ) from self._error

    def _put(self, item):
        self._raise_if_failed()
        with self._cond:
            self._pending += 1
        self._q.put(item)

    def push_sparse_async(self, table, ids, grads):
        self._put(("sparse", table, np.asarray(ids), np.asarray(grads)))

    def push_dense_async(self, table, grad):
        self._put(("dense", table, np.asarray(grad), None))

    def flush(self, timeout=30.0):
        with self._cond:
            if not self._cond.wait_for(lambda: self._pending == 0,
                                       timeout=timeout):
                raise TimeoutError("Communicator flush timed out")
        self._raise_if_failed()

    def stop(self):
        """Drain and shut down the worker; the thread is always joined and
        any recorded push error re-raised AFTER cleanup."""
        err: Optional[BaseException] = None
        try:
            self.flush()
        except BaseException as e:
            err = e
        self._q.put(None)
        self._thread.join(timeout=5)
        if err is not None:
            raise err
