"""Dataset tier: slot-format file ingestion for PS/CTR training.

Reference parity: `python/paddle/fluid/dataset.py` (InMemoryDataset /
QueueDataset facade) over the C++ `framework/data_feed.cc`
MultiSlotDataFeed (slot-format text parsing, `data_feed.proto` config),
`data_set.cc` (in-memory store, local/global shuffle), driven by
`exe.train_from_dataset` (`executor.py:1731`).

Wire format (MultiSlotDataFeed): one sample per line; for each configured
slot, `<n> v1 ... vn` — uint64 slots carry sparse feature ids, float slots
carry dense values. Batches come out as {slot_name: np.ndarray}; ragged
id slots are padded via the LoD bucket policy with a companion
"<slot>.lengths" array.
"""
from __future__ import annotations

import random
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._slots: List[str] = []
        self._types: List[str] = []
        self._filelist: List[str] = []

    def init(self, batch_size: int = 1, use_slots: Sequence[str] = (),
             slot_types: Optional[Sequence[str]] = None, **kw):
        self._batch_size = int(batch_size)
        self._slots = list(use_slots)
        self._types = list(slot_types) if slot_types else \
            ["uint64"] * len(self._slots)
        if len(self._types) != len(self._slots):
            raise ValueError("slot_types length must match use_slots")
        return self

    def set_batch_size(self, bs: int):
        self._batch_size = int(bs)

    def set_filelist(self, files: Sequence[str]):
        self._filelist = list(files)

    def _parse_line(self, line: str):
        toks = line.split()
        out = []
        i = 0
        for ty in self._types:
            if i >= len(toks):
                raise ValueError(f"malformed slot line: {line!r}")
            n = int(toks[i])
            vals = toks[i + 1:i + 1 + n]
            if len(vals) != n:
                raise ValueError(f"slot declared {n} values, got "
                                 f"{len(vals)}: {line!r}")
            i += 1 + n
            out.append(np.asarray(vals, np.uint64 if ty == "uint64"
                                  else np.float32))
        return out

    def _batches_from_samples(self, samples) -> Iterator[Dict[str, np.ndarray]]:
        from ..core.lod import bucket_length
        bs = self._batch_size
        for i in range(0, len(samples) - bs + 1, bs):
            chunk = samples[i:i + bs]
            batch: Dict[str, np.ndarray] = {}
            for si, (name, ty) in enumerate(zip(self._slots, self._types)):
                vals = [s[si] for s in chunk]
                if ty == "uint64":
                    # sparse id slots are ALWAYS bucket-padded + lengths —
                    # per-type, not per-batch, so batch layout (and the XLA
                    # executable cache key) is deterministic. Stays numpy
                    # uint64 host-side (full 64-bit hash ids; jnp would
                    # truncate to uint32 with x64 disabled).
                    lens = [len(v) for v in vals]
                    t = bucket_length(max(lens))
                    arr = np.zeros((len(vals), t), np.uint64)
                    for r, v in enumerate(vals):
                        arr[r, :len(v)] = v
                    batch[name] = arr
                    batch[name + ".lengths"] = np.asarray(lens, np.int32)
                else:
                    if any(len(v) != len(vals[0]) for v in vals):
                        raise ValueError(
                            f"dense float slot {name!r} has ragged lengths; "
                            "declare it uint64 or fix the data")
                    batch[name] = np.stack(vals)
            yield batch


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference InMemoryDataset)."""

    def __init__(self):
        super().__init__()
        self._samples: List = []

    def load_into_memory(self):
        self._samples = []
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        self._samples.append(self._parse_line(line))

    def local_shuffle(self, seed: Optional[int] = None):
        random.Random(seed).shuffle(self._samples)

    def global_shuffle(self, fleet=None, seed: Optional[int] = None):
        # single-host: same as local (reference shuffles across trainers)
        self.local_shuffle(seed)

    def release_memory(self):
        self._samples = []

    def get_memory_data_size(self):
        return len(self._samples)

    def __iter__(self):
        return self._batches_from_samples(self._samples)


class QueueDataset(DatasetBase):
    """Streaming dataset: parse files lazily, no in-memory store
    (reference QueueDataset)."""

    def __iter__(self):
        def stream():
            buf = []
            for path in self._filelist:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            buf.append(self._parse_line(line))
                            if len(buf) == self._batch_size:
                                yield from self._batches_from_samples(buf)
                                buf = []
        return stream()
