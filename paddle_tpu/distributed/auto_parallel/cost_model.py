"""Roofline cost model for parallel-plan selection.

Reference parity: `python/paddle/distributed/auto_parallel/cost_model.py`
(per-op compute/comm cost estimation driving the Planner) and
`cluster.py` (hardware topology the mapper consumes).

TPU-native: costs come from the scaling-book roofline — compute time =
FLOPs / (chips x peak), collective time = bytes x collective-factor /
per-axis bandwidth. The cluster knows its physical ICI mesh; a logical
axis whose span exceeds the ICI domain pays DCN bandwidth instead.
Numbers are v5e-class defaults and overridable per cluster.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple


@dataclass
class ClusterInfo:
    """Per-chip peak + interconnect topology (v5e-ish defaults).

    `ici_mesh` is the physical torus shape of one ICI domain (a v5e-8
    slice is 4x2); logical mesh axes laid over it ride ICI, anything
    spanning more chips than the domain crosses DCN (reference
    `auto_parallel/cluster.py` models the same machine/link split).
    """
    peak_flops: float = 1.97e14      # bf16 FLOPs/s per chip
    ici_bandwidth: float = 4.5e10    # bytes/s per link direction
    dcn_bandwidth: float = 2.5e9     # bytes/s per host
    hbm_bytes: float = 1.6e10        # 16 GB per chip
    hbm_bandwidth: float = 8.2e11    # bytes/s
    collective_latency: float = 1e-5  # fixed per-collective launch/hop cost
    ici_mesh: Tuple[int, ...] = (4, 2)

    @property
    def ici_domain(self) -> int:
        n = 1
        for d in self.ici_mesh:
            n *= d
        return n

    def axis_bandwidth(self, span: int) -> float:
        """Bandwidth available to a logical mesh axis of `span` devices:
        inside one ICI domain it rides ICI links; a larger span must hop
        hosts over DCN, which then bounds the whole collective."""
        return self.ici_bandwidth if span <= self.ici_domain else self.dcn_bandwidth


# collective time factors over a ring of n participants (scaling-book):
def allreduce_time(nbytes, n, bw):
    return 0.0 if n <= 1 else 2.0 * (n - 1) / n * nbytes / bw


def allgather_time(nbytes, n, bw):
    # nbytes = per-shard bytes gathered by everyone
    return 0.0 if n <= 1 else (n - 1) * nbytes / bw


def reducescatter_time(nbytes, n, bw):
    return 0.0 if n <= 1 else (n - 1) / n * nbytes / bw


def alltoall_time(nbytes, n, bw):
    return 0.0 if n <= 1 else (n - 1) / n * nbytes / bw


def p2p_time(nbytes, bw):
    return nbytes / bw


def compute_time(flops, n_chips, cluster: ClusterInfo, mfu=0.4):
    """Wall estimate for `flops` spread over `n_chips` at a realistic MFU."""
    return flops / (n_chips * cluster.peak_flops * mfu)


@dataclass
class PlanCost:
    compute: float
    comm: float
    memory_per_chip: float
    bubble: float = 0.0

    @property
    def total(self):
        return self.compute + self.comm + self.bubble


def train_step_cost(param_bytes, flops_per_step, act_bytes_per_layer,
                    n_layers, dp, mp, cluster: ClusterInfo,
                    sharding_stage=0, pp=1, sp=1,
                    micro_batches=None) -> PlanCost:
    """Cost one hybrid dp x mp x pp x sp training step.

    - dp axis: bucketed gradient all-reduce of the param shard;
    - mp axis: 2 activation all-reduces per layer fwd + 2 bwd (megatron
      pattern, mp_layers.py);
    - pp axis: 1F1B bubble (pp-1)/micro of ideal compute + boundary
      activation p2p per micro-batch;
    - sp axis: ring-attention k/v rotation — (sp-1) block sends of the
      per-chip activation shard per layer, fwd and bwd;
    - memory: params split over mp x pp; grads+adam slots additionally
      over dp for ZeRO stages; activations split over sp (the reason sp
      exists) and mp.
    Each axis pays the bandwidth its SPAN can actually get from the
    physical topology (ICI inside the domain, DCN beyond it).
    """
    n = dp * mp * pp * sp
    lat = cluster.collective_latency
    layers_per_stage = max(n_layers // max(pp, 1), 1)
    shard_param = param_bytes / (mp * pp)

    # The Mapper lays axes out mp-innermost (dp, pp, sp, mp): an axis's
    # PHYSICAL reach is its span times every inner axis's span, so the
    # outer axes cross the ICI domain first — price each by that reach,
    # not by its own size alone.
    reach_mp = mp
    reach_sp = sp * mp
    reach_pp = pp * sp * mp
    reach_dp = n
    comm = allreduce_time(shard_param, dp, cluster.axis_bandwidth(reach_dp)) \
        + (lat if dp > 1 else 0.0)
    # act_bytes_per_layer is computed from the GLOBAL batch (planner.py);
    # dp shards the batch, so every per-chip activation quantity — comm
    # payloads below and the memory term at the end — divides by dp.
    act_local = act_bytes_per_layer / max(dp, 1)
    if mp > 1:
        bw_mp = cluster.axis_bandwidth(reach_mp)
        comm += 4 * layers_per_stage * (
            allreduce_time(act_local / (mp * sp), mp, bw_mp) + lat)
    if sp > 1:
        # ring attention: each of sp-1 steps sends the local K and V block
        bw_sp = cluster.axis_bandwidth(reach_sp)
        per_block = act_local / sp
        comm += 3 * layers_per_stage * (sp - 1) * (
            2 * p2p_time(per_block, bw_sp) + lat)  # fwd + ~2x bwd => 3x
    micro = micro_batches or max(2 * pp, 1)
    comp = compute_time(flops_per_step, n, cluster)
    bubble = comp * (pp - 1) / micro if pp > 1 else 0.0
    if pp > 1:
        bw_pp = cluster.axis_bandwidth(reach_pp)
        comm += (pp - 1) * micro * (
            p2p_time(act_local / (mp * sp), bw_pp) + lat)

    states = 3.0  # grads + adam m/v, in param-bytes units
    if sharding_stage >= 1:
        states = 1.0 + 2.0 / max(dp, 1)
    if sharding_stage >= 2:
        states = 1.0 / max(dp, 1) + 2.0 / max(dp, 1)
    mem = shard_param * (1.0 + states) \
        + layers_per_stage * act_local / (mp * sp)
    return PlanCost(compute=comp, comm=comm, memory_per_chip=mem,
                    bubble=bubble)
