"""Roofline cost model for parallel-plan selection.

Reference parity: `python/paddle/distributed/auto_parallel/cost_model.py`
(per-op compute/comm cost estimation driving the Planner).

TPU-native: costs come from the scaling-book roofline — compute time =
FLOPs / (chips x peak), collective time = bytes x collective-factor / ICI
bandwidth. Numbers are v5e-class defaults and overridable per cluster.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ClusterInfo:
    """Per-chip peak + interconnect figures (v5e-ish defaults)."""
    peak_flops: float = 1.97e14      # bf16 FLOPs/s per chip
    ici_bandwidth: float = 4.5e10    # bytes/s per link direction
    dcn_bandwidth: float = 2.5e9     # bytes/s per host
    hbm_bytes: float = 1.6e10        # 16 GB per chip
    hbm_bandwidth: float = 8.2e11    # bytes/s
    collective_latency: float = 1e-5  # fixed per-collective launch/hop cost


# collective time factors over a ring of n participants (scaling-book):
def allreduce_time(nbytes, n, bw):
    return 0.0 if n <= 1 else 2.0 * (n - 1) / n * nbytes / bw


def allgather_time(nbytes, n, bw):
    # nbytes = per-shard bytes gathered by everyone
    return 0.0 if n <= 1 else (n - 1) * nbytes / bw


def reducescatter_time(nbytes, n, bw):
    return 0.0 if n <= 1 else (n - 1) / n * nbytes / bw


def alltoall_time(nbytes, n, bw):
    return 0.0 if n <= 1 else (n - 1) / n * nbytes / bw


def compute_time(flops, n_chips, cluster: ClusterInfo, mfu=0.4):
    """Wall estimate for `flops` spread over `n_chips` at a realistic MFU."""
    return flops / (n_chips * cluster.peak_flops * mfu)


@dataclass
class PlanCost:
    compute: float
    comm: float
    memory_per_chip: float

    @property
    def total(self):
        return self.compute + self.comm


def train_step_cost(param_bytes, flops_per_step, act_bytes_per_layer,
                    n_layers, dp, mp, cluster: ClusterInfo,
                    sharding_stage=0) -> PlanCost:
    """Cost one hybrid dp x mp training step.

    - dp axis: gradient all-reduce of the param shard each step;
    - mp axis: 2 activation all-reduces per layer fwd + 2 bwd (megatron
      pattern, mp_layers.py) of the per-chip activation bytes;
    - memory: params + grads + adam slots (3x params f32-equiv) per chip,
      divided by mp (tensor shards) and, for ZeRO stages, by dp on slots.
    """
    n = dp * mp
    lat = cluster.collective_latency
    shard_param = param_bytes / mp
    # dp grad allreduce is bucketed (one fused collective); mp pays
    # 4 x n_layers separate activation allreduces, each with launch latency
    comm = allreduce_time(shard_param, dp, cluster.ici_bandwidth) \
        + (lat if dp > 1 else 0.0)
    if mp > 1:
        comm += 4 * n_layers * (
            allreduce_time(act_bytes_per_layer / mp, mp, cluster.ici_bandwidth)
            + lat)
    comp = compute_time(flops_per_step, n, cluster)
    states = 3.0  # grads + adam m/v, in param-bytes units
    if sharding_stage >= 1:
        states = 1.0 + 2.0 / max(dp, 1)
    if sharding_stage >= 2:
        states = 1.0 / max(dp, 1) + 2.0 / max(dp, 1)
    mem = shard_param * (1.0 + states)
    return PlanCost(compute=comp, comm=comm, memory_per_chip=mem)
