"""Dist-attr completion — sharding propagation for unannotated values.

Reference parity: `python/paddle/distributed/auto_parallel/completion.py`
(Completer walks the program and infers dist attrs for every tensor/op from
the user's sparse annotations).

TPU-native redesign: propagation is XLA GSPMD's job. The Completer here
compiles the function AOT with the user's input shardings and reads the
propagated OUTPUT shardings back off the compiled executable — i.e. the
completion algorithm is literally the compiler's, and what we expose is
its verdict (useful for planner costing and for asserting on placement in
tests, the reference's assert-on-dist-attr technique).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .process_mesh import ProcessMesh


def _to_spec(sharding, ndim) -> tuple:
    """NamedSharding/GSPMDSharding -> dims_mapping-style tuple of axis names."""
    if isinstance(sharding, NamedSharding):
        spec = list(sharding.spec) + [None] * (ndim - len(sharding.spec))
        return tuple(s if s is not None else None for s in spec[:ndim])
    return (None,) * ndim


class Completer:
    def __init__(self, process_mesh: ProcessMesh):
        self.process_mesh = process_mesh

    def complete_forward(self, fn: Callable, example_args: Sequence,
                         in_specs: Sequence[Optional[Sequence]]):
        """Returns (out_specs, compiled) where out_specs are the
        GSPMD-propagated output shardings for `fn(*example_args)` given
        the annotated inputs (None spec = let the compiler decide)."""
        mesh = self.process_mesh.to_jax_mesh()
        # None passes through to jax.jit unconstrained: GSPMD chooses
        in_shardings = tuple(
            NamedSharding(mesh, P(*sp)) if sp is not None else None
            for sp in in_specs)
        jitted = jax.jit(fn, in_shardings=in_shardings)
        compiled = jitted.lower(*example_args).compile()
        outs = compiled.output_shardings
        shapes = jax.eval_shape(fn, *example_args)
        flat_sh, _ = jax.tree.flatten(outs)
        flat_shape, _ = jax.tree.flatten(shapes, is_leaf=lambda x: hasattr(x, "ndim"))
        specs = [
            _to_spec(sh, sp.ndim) for sh, sp in zip(flat_sh, flat_shape)
        ]
        return specs, compiled
