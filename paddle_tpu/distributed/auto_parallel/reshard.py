"""Reshard — move a tensor between shardings/meshes.

Reference parity: `python/paddle/distributed/auto_parallel/reshard.py`
(Reshard inserts slice/concat/send/recv ops to convert a tensor from one
dist_attr to another between pipeline/parallel regions).

TPU-native: resharding is a `jax.device_put` onto the target
NamedSharding — XLA emits the minimal collective (all-gather, all-to-all,
collective-permute or slice) on ICI; inside jit the same conversion is a
`with_sharding_constraint`. No manual send/recv graph surgery survives.
"""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ...core.tensor import Tensor
from .interface import place_value, validated_sharding
from .process_mesh import ProcessMesh


def reshard(x, process_mesh: ProcessMesh, shard_spec: Sequence):
    """Return `x` placed with the new per-dim sharding (None=replicated)."""
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    sharding = validated_sharding(process_mesh, shard_spec, t._value.ndim)
    out = Tensor(place_value(t._value, sharding),
                 stop_gradient=t.stop_gradient)
    out.dist_attr = tuple(s if s else None for s in shard_spec)
    out.process_mesh = process_mesh
    return out
