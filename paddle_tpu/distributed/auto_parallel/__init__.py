"""Semi-auto parallel: annotate -> complete -> plan -> run.

Reference parity: `python/paddle/distributed/auto_parallel/` (interface.py
shard_tensor/shard_op, process_mesh.py, completion.py, partitioner.py,
reshard.py, planner.py, cost_model.py, engine.py — 21 files).

TPU-native collapse: Partitioner + Reshard + much of Completion are XLA
GSPMD's job; what survives is the user annotate API, the planner/cost
model choosing the mesh, the completion *query* (reading propagated
shardings off the compiled executable), and the Engine driver.
"""
from .process_mesh import ProcessMesh  # noqa: F401
from .interface import shard_tensor, shard_op  # noqa: F401
from .completion import Completer  # noqa: F401
from .reshard import reshard  # noqa: F401
from .cost_model import ClusterInfo, PlanCost, train_step_cost  # noqa: F401
from .planner import Mapper, ParallelPlan, Partitioner, Planner  # noqa: F401
from .engine import Engine  # noqa: F401
