"""Planner — pick a dp x mp x sharding plan for a model on N devices.

Reference parity: `python/paddle/distributed/auto_parallel/planner.py`
(search over partitioned programs scored by the cost model; the mapper
assigns ranks to hardware).

TPU-native: the search space is mesh factorizations (dp, mp) of the chip
count plus a ZeRO stage; each candidate is scored with the roofline cost
model and infeasible ones (HBM overflow) are discarded. Deterministic and
cheap — no program partitioning is needed because GSPMD does the actual
slicing from the chosen mesh + annotations.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .cost_model import ClusterInfo, PlanCost, train_step_cost


@dataclass
class ParallelPlan:
    dp: int
    mp: int
    sharding_stage: int
    cost: PlanCost
    mesh_shape: dict = field(default_factory=dict)

    def __post_init__(self):
        self.mesh_shape = {"dp": self.dp, "mp": self.mp}


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


class Planner:
    def __init__(self, n_devices: int, cluster: Optional[ClusterInfo] = None):
        self.n_devices = n_devices
        self.cluster = cluster or ClusterInfo()

    def model_stats(self, model, batch_size: int, seq_len: int = 1):
        """(param_bytes, flops_per_step, act_bytes_per_layer, n_layers)
        from a live Layer tree — 6*N*tokens matmul flops (PaLM rule)."""
        params = list(model.parameters())
        n_params = sum(int(np.prod(p.shape)) for p in params)
        param_bytes = 4.0 * n_params
        tokens = batch_size * max(seq_len, 1)
        flops = 6.0 * n_params * tokens
        mats = [p for p in params if len(p.shape) == 2]
        n_layers = max(len(mats), 1)
        hidden = max((p.shape[-1] for p in mats), default=1)
        act_bytes = 2.0 * tokens * hidden  # bf16 activations
        return param_bytes, flops, act_bytes, n_layers

    def candidates(self, param_bytes, flops, act_bytes, n_layers) -> List[ParallelPlan]:
        out = []
        for mp in _divisors(self.n_devices):
            dp = self.n_devices // mp
            for stage in (0, 1, 2):
                if stage > 0 and dp == 1:
                    continue
                c = train_step_cost(param_bytes, flops, act_bytes, n_layers,
                                    dp, mp, self.cluster, sharding_stage=stage)
                if c.memory_per_chip <= self.cluster.hbm_bytes:
                    out.append(ParallelPlan(dp, mp, stage, c))
        return out

    def plan(self, model=None, batch_size: int = 1, seq_len: int = 1,
             stats=None) -> ParallelPlan:
        """Best feasible plan (min step time; ties -> smaller mp, then
        smaller sharding stage — less comm machinery for equal speed)."""
        if stats is None:
            stats = self.model_stats(model, batch_size, seq_len)
        cands = self.candidates(*stats)
        if not cands:
            raise RuntimeError(
                "no feasible plan: model exceeds HBM at every dp x mp x "
                "sharding candidate")
        return min(cands, key=lambda p: (p.cost.total, p.mp, p.sharding_stage))
