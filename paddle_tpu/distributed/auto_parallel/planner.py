"""Planner — pick a dp x mp x pp x sp plan for a model on N devices.

Reference parity: `python/paddle/distributed/auto_parallel/planner.py`
(search over partitioned programs scored by the cost model),
`partitioner.py` (apply the chosen distribution to the program) and
`mapper.py` (assign logical ranks to physical hardware).

TPU-native: the search space is mesh factorizations (dp, mp, pp, sp) of
the chip count plus a ZeRO stage; each candidate is scored with the
topology-aware roofline cost model and infeasible ones (HBM overflow)
are discarded. The Partitioner emits GSPMD-level artifacts (mesh shape,
param specs, pipeline stage split) instead of a rewritten ProgramDesc —
XLA does the actual slicing. The Mapper orders logical axes onto the
physical ICI mesh so the most communication-intensive axis gets the
nearest neighbors.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .cost_model import ClusterInfo, PlanCost, train_step_cost


@dataclass
class ParallelPlan:
    dp: int
    mp: int
    sharding_stage: int
    cost: PlanCost
    pp: int = 1
    sp: int = 1
    mesh_shape: dict = field(default_factory=dict)

    def __post_init__(self):
        # 'dp' is ALWAYS present (consumers rename it to 'sharding' for
        # ZeRO); other axes appear only when >1
        self.mesh_shape = {"dp": self.dp}
        self.mesh_shape.update({k: v for k, v in
                                (("mp", self.mp), ("pp", self.pp),
                                 ("sp", self.sp)) if v > 1})


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


class Planner:
    def __init__(self, n_devices: int, cluster: Optional[ClusterInfo] = None,
                 max_pp: int = 8, enable_sp: bool = True):
        self.n_devices = n_devices
        self.cluster = cluster or ClusterInfo()
        self.max_pp = max_pp
        self.enable_sp = enable_sp

    def model_stats(self, model, batch_size: int, seq_len: int = 1):
        """(param_bytes, flops_per_step, act_bytes_per_layer, n_layers)
        from a live Layer tree — 6*N*tokens matmul flops (PaLM rule)."""
        params = list(model.parameters())
        n_params = sum(int(np.prod(p.shape)) for p in params)
        param_bytes = 4.0 * n_params
        tokens = batch_size * max(seq_len, 1)
        flops = 6.0 * n_params * tokens
        mats = [p for p in params if len(p.shape) == 2]
        n_layers = max(len(mats), 1)
        hidden = max((p.shape[-1] for p in mats), default=1)
        act_bytes = 2.0 * tokens * hidden  # bf16 activations
        return param_bytes, flops, act_bytes, n_layers

    def candidates(self, param_bytes, flops, act_bytes, n_layers,
                   seq_len: int = 1) -> List[ParallelPlan]:
        out = []
        n = self.n_devices
        for mp in _divisors(n):
            for pp in _divisors(n // mp):
                if pp > min(self.max_pp, n_layers):
                    continue
                for sp in _divisors(n // (mp * pp)):
                    if sp > 1 and (not self.enable_sp or seq_len < 2 * sp):
                        continue
                    dp = n // (mp * pp * sp)
                    for stage in (0, 1, 2):
                        if stage > 0 and dp == 1:
                            continue
                        if stage > 0 and pp > 1:
                            continue  # ZeRO+pp composition not searched
                        c = train_step_cost(param_bytes, flops, act_bytes,
                                            n_layers, dp, mp, self.cluster,
                                            sharding_stage=stage, pp=pp,
                                            sp=sp)
                        if c.memory_per_chip <= self.cluster.hbm_bytes:
                            out.append(ParallelPlan(dp, mp, stage, c,
                                                    pp=pp, sp=sp))
        return out

    def plan(self, model=None, batch_size: int = 1, seq_len: int = 1,
             stats=None) -> ParallelPlan:
        """Best feasible plan (min step time; ties -> fewer exotic axes:
        smaller mp, then pp, then sp, then sharding stage)."""
        if stats is None:
            stats = self.model_stats(model, batch_size, seq_len)
        cands = self.candidates(*stats, seq_len=seq_len)
        if not cands:
            raise RuntimeError(
                "no feasible plan: model exceeds HBM at every "
                "dp x mp x pp x sp x sharding candidate")
        return min(cands, key=lambda p: (p.cost.total, p.mp, p.pp, p.sp,
                                         p.sharding_stage))


class Partitioner:
    """Turn a ParallelPlan into GSPMD-level artifacts for a concrete model.

    Reference parity: `auto_parallel/partitioner.py` rewrites the serial
    program into a distributed one; here the 'program' is the (mesh,
    annotations) pair GSPMD consumes plus a contiguous pipeline-stage
    split of the layer list.
    """

    def __init__(self, plan: ParallelPlan):
        self.plan = plan

    def stage_split(self, n_layers: int) -> List[int]:
        """stage index per layer — contiguous groups whose sizes differ by
        at most one, so NO stage is ever empty (pp <= n_layers)."""
        pp = max(self.plan.pp, 1)
        return [min(i * pp // n_layers, pp - 1) for i in range(n_layers)]

    def param_specs(self, shapes) -> List[tuple]:
        """PartitionSpecs for an ordered parameter list under the plan:
        consecutive 2D matmul weights alternate column-parallel then
        row-parallel (megatron pairing — one all-reduce per pair instead
        of an activation reshard between every matmul; same policy as
        Engine._annotate_mp). Everything else replicates."""
        out = []
        col = True
        for shape in shapes:
            if self.plan.mp > 1 and len(shape) == 2:
                out.append((None, "mp") if col else ("mp", None))
                col = not col
            else:
                out.append(tuple(None for _ in shape))
        return out

    def partition(self, model):
        """(mesh_shape, {param_name: spec}, stage_of_layer) for the model."""
        names, shapes = [], []
        for name, p in model.named_parameters():
            names.append(name)
            shapes.append(tuple(p.shape))
        specs: Dict[str, tuple] = dict(zip(names, self.param_specs(shapes)))
        try:
            n_layers = len(model.layers)
        except (AttributeError, TypeError):
            n_layers = sum(1 for _ in model.children())
        return self.plan.mesh_shape, specs, self.stage_split(max(n_layers, 1))


class Mapper:
    """Order logical mesh axes onto the physical device mesh.

    Reference parity: `auto_parallel/mapper.py` maps ranks to machines by
    comm volume. Here: jax mesh axes are laid out so the LAST axis gets
    adjacent devices (best locality on the ICI torus); we therefore order
    axes by descending per-step communication intensity — mp (per-layer
    activation allreduces) > sp (ring p2p per layer) > pp (per-micro p2p)
    > dp (one bucketed grad allreduce) — so the heaviest talker sits on
    neighboring chips.
    """

    ORDER = ("dp", "pp", "sp", "mp")  # least -> most comm-intensive

    def __init__(self, cluster: Optional[ClusterInfo] = None):
        self.cluster = cluster or ClusterInfo()

    def axis_order(self, mesh_shape: Dict[str, int]) -> List[str]:
        return [a for a in self.ORDER if mesh_shape.get(a, 1) >= 1
                and a in mesh_shape]

    def device_mesh(self, mesh_shape: Dict[str, int]):
        """A jax Mesh with axes ordered for ICI locality."""
        import jax
        from jax.sharding import Mesh
        names = self.axis_order(mesh_shape)
        sizes = [mesh_shape[a] for a in names]
        n = int(np.prod(sizes)) if sizes else 1
        devs = np.asarray(jax.devices()[:n]).reshape(sizes or (1,))
        return Mesh(devs, tuple(names) or ("dp",))
