"""Annotate API: `shard_tensor` / `shard_op`.

Reference parity: `python/paddle/distributed/auto_parallel/interface.py:1`
(shard_tensor attaches a dist_attr {process_mesh, dims_mapping} to a
variable; shard_op annotates an op's inputs/outputs).

TPU-native: annotations ARE the mechanism — eager tensors are device_put
onto the mesh with a NamedSharding; traced values get
`lax.with_sharding_constraint`, and XLA's GSPMD pass plays the reference's
"completion" role for everything unannotated.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .process_mesh import ProcessMesh


def validated_sharding(process_mesh: ProcessMesh, shard_spec: Sequence,
                       ndim: int) -> "jax.sharding.NamedSharding":
    """Validate a per-dim spec against the mesh + tensor rank and build the
    NamedSharding (shared by shard_tensor and reshard)."""
    if len(shard_spec) != ndim:
        raise ValueError(
            f"shard_spec {list(shard_spec)} rank != tensor rank {ndim}")
    for s in shard_spec:
        if s is not None and s and s not in process_mesh.dim_names:
            raise ValueError(f"unknown mesh dim {s!r}; mesh has "
                             f"{process_mesh.dim_names}")
    return NamedSharding(process_mesh.to_jax_mesh(),
                         P(*[s if s else None for s in shard_spec]))


def place_value(value, sharding):
    """eager -> device_put; traced (inside jit) -> sharding constraint."""
    if isinstance(value, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(value, sharding)
    return jax.device_put(value, sharding)


def shard_tensor(x, process_mesh: ProcessMesh, shard_spec: Sequence):
    """Place `x` on the mesh with per-dim axis names (None = replicated).

    Returns the same Tensor with `dist_attr` set; data is moved/annotated:
    - eager value -> `jax.device_put` with a NamedSharding;
    - traced value (inside jit) -> `with_sharding_constraint`.
    """
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    sharding = validated_sharding(process_mesh, shard_spec, t._value.ndim)
    t._value = place_value(t._value, sharding)
    t.dist_attr = tuple(s if s else None for s in shard_spec)
    t.process_mesh = process_mesh
    return t


def shard_op(fn: Callable, process_mesh: ProcessMesh,
             in_specs: Optional[Sequence] = None,
             out_specs: Optional[Sequence] = None) -> Callable:
    """Wrap `fn` so its tensor inputs/outputs are constrained to the given
    shardings (the reference's shard_op dist-attr annotation)."""
    def wrapped(*args, **kwargs):
        if in_specs is not None:
            args = tuple(
                shard_tensor(a, process_mesh, sp) if sp is not None else a
                for a, sp in zip(args, in_specs)
            ) + tuple(args[len(in_specs):])
        out = fn(*args, **kwargs)
        if out_specs is None:
            return out
        single = not isinstance(out, (tuple, list))
        outs = [out] if single else list(out)
        outs = [shard_tensor(o, process_mesh, sp) if sp is not None else o
                for o, sp in zip(outs, out_specs)]
        return outs[0] if single else type(out)(outs)
    return wrapped
