"""Engine — one-call semi-auto-parallel training/eval/predict.

Reference parity: `python/paddle/distributed/auto_parallel/engine.py`
(Engine.prepare/fit/evaluate/predict: complete annotations, partition the
program over the cluster, insert reshards, run).

TPU-native: prepare() asks the Planner for a dp x mp x ZeRO plan (or takes
the user's), builds the mesh, auto-annotates unannotated 2-D weights in the
megatron alternate column/row pattern (mp_layers.py convention), and
compiles ONE SPMD train step via GSPMD — partitioning, reshard insertion
and collective choice all happen inside XLA.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ...core.tensor import Tensor
from ...parallel.spmd import SPMDTrainStep
from ...parallel.topology import create_mesh
from .cost_model import ClusterInfo
from .planner import ParallelPlan, Planner


class Engine:
    def __init__(self, model, loss_fn: Optional[Callable] = None,
                 optimizer=None, cluster: Optional[ClusterInfo] = None,
                 n_devices: Optional[int] = None):
        import jax
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.cluster = cluster or ClusterInfo()
        self.n_devices = n_devices or jax.device_count()
        self.plan: Optional[ParallelPlan] = None
        self.mesh = None
        self._step = None
        self._eval_fn = None

    # ---- planning ----
    def prepare(self, batch_size: int, seq_len: int = 1,
                plan: Optional[ParallelPlan] = None, amp_dtype=None):
        # Engine executes via SPMDTrainStep, whose axes are dp/mp/sharding:
        # the auto-search is restricted to that executable subspace (pp/sp
        # plans are for HybridCommunicateGroup-driven engines — picking one
        # here would run pp*sp redundant replicas while the cost model
        # credits a speedup)
        self.plan = plan or Planner(self.n_devices, self.cluster,
                                    max_pp=1, enable_sp=False).plan(
            self.model, batch_size, seq_len)
        axes = dict(self.plan.mesh_shape)
        if self.plan.sharding_stage > 0:
            # ZeRO over the data ranks: name the axis so SPMDTrainStep
            # applies slot/param sharding to it
            axes = {"sharding": axes.pop("dp"), **axes}
        axes = {k: v for k, v in axes.items() if v > 1} or {"dp": 1}
        # Mapper ordering: heaviest talker (mp) innermost = adjacent devices
        # on the physical mesh; 'sharding' ranks like 'dp' (outermost)
        from .planner import Mapper
        rank = {a: i for i, a in enumerate(Mapper.ORDER)}
        axes = dict(sorted(axes.items(),
                           key=lambda kv: rank.get(
                               "dp" if kv[0] == "sharding" else kv[0], 0)))
        self.mesh = create_mesh(axes)
        if self.plan.mp > 1:
            self._annotate_mp()
        if self.optimizer is not None and self.loss_fn is not None:
            self._step = SPMDTrainStep(
                self.model, self.loss_fn, self.optimizer, mesh=self.mesh,
                sharding_stage=self.plan.sharding_stage, amp_dtype=amp_dtype)
        return self.plan

    def _annotate_mp(self):
        """Alternate column/row tensor-parallel annotation on consecutive
        2-D weights (megatron pairing: col-parallel then row-parallel needs
        only one all-reduce per pair — mp_layers.py convention)."""
        mp = self.plan.mp
        col = True
        for layer in self.model.sublayers(include_self=True):
            w = getattr(layer, "weight", None)
            if w is None or len(w.shape) != 2 or w.dist_attr is not None:
                continue
            din, dout = w.shape
            if col and dout % mp == 0:
                w.dist_attr = (None, "mp")
                b = getattr(layer, "bias", None)
                if b is not None and len(b.shape) == 1 and b.shape[0] == dout:
                    b.dist_attr = ("mp",)
                col = False
            elif not col and din % mp == 0:
                w.dist_attr = ("mp", None)
                col = True

    # ---- run ----
    def fit(self, x, y, epochs: int = 1, batch_size: int = 32,
            log_freq: int = 0):
        """x/y: numpy arrays (full dataset); returns per-step loss list."""
        if self._step is None:
            self.prepare(batch_size, seq_len=(x.shape[1] if x.ndim > 1 else 1))
        n = len(x)
        losses = []
        for _ in range(epochs):
            for i in range(0, n - batch_size + 1, batch_size):
                loss = self._step(Tensor(np.asarray(x[i:i + batch_size])),
                                  Tensor(np.asarray(y[i:i + batch_size])))
                losses.append(float(loss))
                if log_freq and len(losses) % log_freq == 0:
                    print(f"[engine] step {len(losses)} loss {losses[-1]:.4f}")
        return losses

    def evaluate(self, x, y, batch_size: int = 32):
        total, cnt = 0.0, 0
        for i in range(0, len(x) - batch_size + 1, batch_size):
            out = self.model(Tensor(np.asarray(x[i:i + batch_size])))
            loss = self.loss_fn(out, Tensor(np.asarray(y[i:i + batch_size])))
            total += float(loss)
            cnt += 1
        return total / max(cnt, 1)

    def predict(self, x, batch_size: int = 32):
        outs = []
        for i in range(0, len(x), batch_size):
            outs.append(np.asarray(
                self.model(Tensor(np.asarray(x[i:i + batch_size])))._value))
        return np.concatenate(outs, 0)

    def cost(self):
        """Planner's roofline estimate for the prepared plan (seconds/step)."""
        if self.plan is None:
            raise RuntimeError("call prepare() first")
        return self.plan.cost
