"""ProcessMesh — the auto-parallel device grid.

Reference parity: `python/paddle/distributed/auto_parallel/process_mesh.py`
(ProcessMesh holding an N-D array of process ids + dim names, used by
`shard_tensor`/`shard_op` annotations).

TPU-native: a ProcessMesh is a thin, picklable description that lowers to a
`jax.sharding.Mesh` over real (or virtual) devices; dim names become mesh
axis names, so annotated dims ride GSPMD/ICI collectives directly.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[Sequence[str]] = None):
        self._ids = np.asarray(mesh, dtype=np.int64)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(self._ids.ndim)]
        if len(dim_names) != self._ids.ndim:
            raise ValueError(
                f"dim_names {list(dim_names)} rank != mesh rank {self._ids.ndim}")
        self.dim_names: List[str] = list(dim_names)
        self._jax_mesh: Optional[Mesh] = None

    @property
    def shape(self):
        return tuple(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def process_ids(self):
        return [int(i) for i in self._ids.reshape(-1)]

    def to_jax_mesh(self) -> Mesh:
        """Materialize over the runtime's devices (process id -> device)."""
        if self._jax_mesh is None:
            devs = jax.devices()
            if self._ids.size > len(devs):
                raise ValueError(
                    f"ProcessMesh needs {self._ids.size} devices, "
                    f"have {len(devs)}")
            arr = np.empty(self._ids.shape, dtype=object)
            for idx, pid in np.ndenumerate(self._ids):
                arr[idx] = devs[int(pid)]
            self._jax_mesh = Mesh(arr, tuple(self.dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self.dim_names == other.dim_names
                and np.array_equal(self._ids, other._ids))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"
