"""DataLoader with background prefetch + device double-buffering.

Reference parity: `python/paddle/io/DataLoader` → `fluid/reader.py:146` with
multiprocess workers (`dataloader_iter.py`) and the C++ double-buffer
(`operators/reader/buffered_reader.cc`). TPU-first: worker threads build
numpy batches; a prefetch queue overlaps host batch assembly + H2D transfer
with device compute (XLA async dispatch gives the second buffer for free).
The heavy inner loop (batch gather/stack) can run through the native C++
prefetcher (`paddle_tpu._native`) when built.
"""
from __future__ import annotations

import queue
import threading
import time as _time
from typing import Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .. import monitor as _monitor
from .. import obs as _obs
from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler
from ..utils import syncwatch as _syncwatch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor, jax.Array)):
        return Tensor(jnp.stack([b._value if isinstance(b, Tensor) else b for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(jnp.asarray(np.stack(batch)))
    if isinstance(sample, (int, np.integer)):
        return Tensor(jnp.asarray(np.asarray(batch, dtype=np.int64 if False else np.int32)))
    if isinstance(sample, float):
        return Tensor(jnp.asarray(np.asarray(batch, dtype=np.float32)))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _PrefetchIter:
    def __init__(self, loader):
        self.loader = loader
        self.batch_sampler_iter = iter(loader.batch_sampler)
        self.queue = queue.Queue(maxsize=loader.prefetch_factor)
        self._stop = threading.Event()
        self._threads = []
        n_workers = max(1, loader.num_workers)
        self._n_workers = n_workers
        self._done_workers = 0
        self._index_lock = threading.Lock()
        self._seq = 0
        self._pending = {}
        self._emit = 0
        for _ in range(n_workers):
            t = _syncwatch.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)

    def _next_indices(self):
        with self._index_lock:
            try:
                idx = next(self.batch_sampler_iter)
            except StopIteration:
                return None, None
            seq = self._seq
            self._seq += 1
            return seq, idx

    def _worker(self):
        ds, collate = self.loader.dataset, self.loader.collate_fn
        while not self._stop.is_set():
            seq, indices = self._next_indices()
            if seq is None:
                self.queue.put((None, None))
                return
            try:
                if _monitor._ENABLED:
                    _tb = _time.time()
                    samples = [ds[i] for i in indices]
                    batch = collate(samples)
                    _monitor.observe("io.dataloader.worker_batch",
                                     _time.time() - _tb)
                else:
                    samples = [ds[i] for i in indices]
                    batch = collate(samples)
                self.queue.put((seq, batch))
            except Exception as e:  # propagate to consumer
                self.queue.put((seq, e))
                return

    def __next__(self):
        # re-order worker results to sampler order
        while True:
            if self._emit in self._pending:
                batch = self._pending.pop(self._emit)
                self._emit += 1
                if isinstance(batch, Exception):
                    raise batch
                return batch
            # all workers done → every produced batch is already queued/pending
            if self._done_workers >= self._n_workers and self.queue.empty():
                raise StopIteration
            if _monitor._ENABLED or _obs._TL_ENABLED:
                # how long the consumer stalls on the workers: the signal
                # that the input pipeline (not the device) is the bottleneck
                _tw = _time.time()
                seq, batch = self.queue.get()
                _t1 = _time.time()
                if _monitor._ENABLED:
                    _monitor.observe("io.dataloader.queue_wait", _t1 - _tw)
                # timeline: this wait sits BETWEEN steps, so it folds into
                # the next step record's `between` bucket (obs/timeline.py)
                _obs.add_phase("data_wait", _t1 - _tw, _tw, _t1)
            else:
                seq, batch = self.queue.get()
            if seq is None:
                self._done_workers += 1
                continue
            self._pending[seq] = batch

    def __iter__(self):
        return self

    def __del__(self):
        self._stop.set()


class _SimpleIter:
    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader.batch_sampler)

    def __next__(self):
        indices = next(self.it)
        samples = [self.loader.dataset[i] for i in indices]
        return self.loader.collate_fn(samples)

    def __iter__(self):
        return self


class _IterableIter:
    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader.dataset)

    def __next__(self):
        batch = []
        for _ in range(self.loader.batch_size):
            try:
                batch.append(next(self.it))
            except StopIteration:
                break
        if not batch or (self.loader.drop_last and len(batch) < self.loader.batch_size):
            raise StopIteration
        return self.loader.collate_fn(batch)

    def __iter__(self):
        return self


def _wrap_numpy(obj):
    """Parent-side: numpy batch structure -> Tensors (the single H2D hop)."""
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, tuple):
        return tuple(_wrap_numpy(o) for o in obj)
    if isinstance(obj, list):
        return [_wrap_numpy(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _wrap_numpy(v) for k, v in obj.items()}
    return obj


class DataLoader:
    """`num_workers>0` uses real worker PROCESSES with shared-memory numpy
    transport (`paddle_tpu.io.worker`, dataloader_iter.py parity); pass
    `use_shared_memory=False` to ship batches by pickling, or
    `use_buffer_reader=False` to force the in-process thread prefetcher.
    A custom `collate_fn` runs in the worker and must return numpy (never
    device arrays); the parent performs the H2D transfer."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate_fn
        self.worker_collate_fn = collate_fn  # None -> worker numpy collate
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.use_buffer_reader = use_buffer_reader
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if not self._iterable:
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last)
        else:
            self.batch_sampler = None

    def _post_collate(self, np_batch):
        return _wrap_numpy(np_batch)

    def __iter__(self):
        if self._iterable:
            return _IterableIter(self)
        if self.num_workers > 0:
            if self.use_buffer_reader:
                from .worker import MultiprocessIter
                return MultiprocessIter(self)
            return _PrefetchIter(self)
        return _SimpleIter(self)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)
