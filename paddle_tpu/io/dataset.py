"""Dataset abstractions.

Reference parity: `python/paddle/io/` (Dataset, IterableDataset,
TensorDataset, ComposeDataset, ChainDataset, Subset, random_split).
"""
from __future__ import annotations

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not indexable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self._cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self._cum[-1])

    def __getitem__(self, idx):
        di = int(np.searchsorted(self._cum, idx, side="right"))
        prev = 0 if di == 0 else int(self._cum[di - 1])
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out
