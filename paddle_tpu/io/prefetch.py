"""Async double-buffered host→device prefetch — the device feed queue.

Reference parity: the DataFeed / `operators/reader/buffered_reader.cc`
double-buffer (PAPER.md §2): the C++ reader stages the NEXT batch on the
device while the current step computes, so H2D transfer and host batch
assembly never appear on the step's critical path. TPU-native version: a
feeder thread runs `jax.device_put` (with the step's input shardings when
the step is SPMD) `FLAGS_prefetch_depth` batches ahead of the consumer.

Sits BETWEEN any batch iterable (`io.DataLoader`, a list of numpy tuples, a
generator) and `TrainStep`/`SPMDTrainStep`: batches come out as Tensors
whose arrays are already device-resident, so the step's own `h2d` phase
collapses to a metadata check and the consumer's `data_wait` collapses to a
queue pop of a ready item.

Timeline booking (obs plane): the feeder's device_put time is booked as
`prefetch_h2d` through `add_async_phase` — it ran concurrently with steps,
so it must stay visible WITHOUT being charged against any step window (no
double-count against device_compute, and the phases-sum≈wall invariant
holds). The consumer's residual stall books `data_wait` as before.

TrainGuard contract: the resume cursor counts CONSUMED batches only
(`Model.fit` sets the cursor as it pulls from this iterator), so a
preemption drops at most `depth` staged batches — they are re-produced
from the source on resume, never double-trained. `stats()["in_flight"]`
exposes the staged count; `close()` discards it.

Disabled path: `maybe_wrap` is ONE module-attribute check (`_ENABLED`,
kept in sync with FLAGS_prefetch by watch_flag) — the PR-1-style overhead
contract, enforced by a tier-1 guard test.
"""
from __future__ import annotations

import queue
import threading
import time as _time
from typing import Any, Optional, Sequence

import jax

from .. import monitor as _monitor
from .. import obs as _obs
from ..obs import memory as _mem
from ..core import flags as _flags
from ..core.tensor import Tensor
from ..utils import syncwatch as _syncwatch

__all__ = ["DevicePrefetcher", "maybe_wrap"]

_ENABLED: bool = bool(_flags.flag("prefetch"))


def _sync_enabled(v) -> None:
    global _ENABLED
    _ENABLED = bool(v)


_flags.watch_flag("prefetch", _sync_enabled)


def maybe_wrap(source, step=None, depth: Optional[int] = None):
    """Wrap `source` in a DevicePrefetcher when FLAGS_prefetch is on;
    return it unchanged otherwise. The disabled path is this one attribute
    check — no allocation, no thread."""
    if not _ENABLED:
        return source
    return DevicePrefetcher(source, step=step, depth=depth)


def _device_put_batch(batch, shardings):
    """numpy/Tensor batch structure -> device-resident Tensor structure.
    `shardings` is a flat per-position list (or None) for tuple batches."""
    if isinstance(batch, (list, tuple)):
        out = []
        for i, b in enumerate(batch):
            sh = shardings[i] if shardings is not None and \
                i < len(shardings) else None
            out.append(_device_put_one(b, sh))
        return tuple(out) if isinstance(batch, tuple) else out
    return _device_put_one(batch, shardings[0] if shardings else None)


def _device_put_one(b, sharding):
    if isinstance(b, Tensor):
        arr = jax.device_put(b._value, sharding) if sharding is not None \
            else b._value
        return Tensor(arr) if arr is not b._value else b
    if isinstance(b, dict):
        return {k: _device_put_one(v, sharding) for k, v in b.items()}
    return Tensor(jax.device_put(b, sharding))


class _Session:
    """One epoch's feeder thread + bounded device queue."""

    _END = object()

    def __init__(self, it, depth: int, shardings, step):
        self._it = it
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._shardings = shardings
        self._step = step
        self._produced = 0
        self._consumed = 0
        self._thread = _syncwatch.Thread(target=self._feed, daemon=True,
                                        name="prefetch-feeder")
        self._thread.start()

    # ---- feeder side ----
    def _resolve_shardings(self, batch):
        """First batch: ask the step for its input shardings (SPMD steps
        build + expose them; single-device steps return None -> plain
        device_put to the default device)."""
        if self._shardings is not None or self._step is None:
            return
        fn = getattr(self._step, "input_shardings", None)
        if fn is not None:
            try:
                self._shardings = fn(*batch) if isinstance(batch, (list, tuple)) \
                    else fn(batch)
            except Exception:
                self._shardings = None
        self._step = None  # resolve once

    def _feed(self) -> None:
        mon = _monitor._ENABLED
        try:
            for batch in self._it:
                if self._stop.is_set():
                    return
                self._resolve_shardings(batch)
                _t0 = _time.time()
                staged = _device_put_batch(batch, self._shardings)
                _t1 = _time.time()
                if _mem._ENABLED:
                    _mem.tag("prefetch_staging", staged,
                             origin="DevicePrefetcher")
                if _obs._TL_ENABLED:
                    # hidden time: ran under the previous step, so it books
                    # through add_async_phase (between bucket), never inside
                    # a step window
                    _obs.add_async_phase("prefetch_h2d", _t1 - _t0, _t0, _t1)
                if mon:
                    _monitor.observe("io.prefetch.h2d", _t1 - _t0)
                    _monitor.count("io.prefetch.batches")
                while not self._stop.is_set():
                    try:
                        self._q.put(staged, timeout=0.1)
                        self._produced += 1
                        break
                    except queue.Full:
                        if mon:
                            _monitor.count("io.prefetch.stalls")
        except Exception as e:  # propagate to the consumer
            self._q.put(e)
            return
        self._q.put(self._END)

    # ---- consumer side ----
    def next(self):
        if _monitor._ENABLED or _obs._TL_ENABLED:
            _tw = _time.time()
            item = self._q.get()
            _t1 = _time.time()
            if _monitor._ENABLED:
                _monitor.observe("io.prefetch.queue_wait", _t1 - _tw)
            # residual stall (feeder slower than the device): between-steps
            # data_wait, exactly like the DataLoader consumer booking
            _obs.add_phase("data_wait", _t1 - _tw, _tw, _t1)
        else:
            item = self._q.get()
        if item is self._END:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        self._consumed += 1
        return item

    @property
    def in_flight(self) -> int:
        return self._produced - self._consumed

    def close(self) -> None:
        self._stop.set()
        # drain so a feeder blocked on put() can observe the stop event
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)


class DevicePrefetcher:
    """Re-iterable device feed queue over any batch iterable. Each
    `iter()` starts a fresh feeder session (one per epoch); `close()`
    stops the active session and discards staged batches."""

    def __init__(self, source, step=None, depth: Optional[int] = None,
                 shardings: Optional[Sequence[Any]] = None):
        self.source = source
        self.depth = int(depth) if depth is not None \
            else int(_flags.flag("prefetch_depth"))
        self._shardings = list(shardings) if shardings is not None else None
        self._step = step
        self._session: Optional[_Session] = None

    def __iter__(self):
        if self._session is not None:
            self._session.close()
        self._session = _Session(iter(self.source), self.depth,
                                 self._shardings, self._step)
        return self

    def __next__(self):
        if self._session is None:
            iter(self)
        return self._session.next()

    def __len__(self):
        return len(self.source)

    def stats(self) -> dict:
        s = self._session
        return {"depth": self.depth,
                "in_flight": s.in_flight if s is not None else 0,
                "produced": s._produced if s is not None else 0,
                "consumed": s._consumed if s is not None else 0}

    def close(self) -> None:
        """Stop the feeder and DROP staged batches. Safe after a
        preemption: the resume cursor only counts consumed batches, so the
        dropped ones are re-produced from the source on the next run."""
        if self._session is not None:
            dropped = self._session.in_flight
            if dropped and _monitor._ENABLED:
                _monitor.count("io.prefetch.dropped", dropped)
            self._session.close()
            self._session = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
