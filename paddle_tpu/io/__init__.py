"""paddle.io parity namespace."""
from .dataset import (  # noqa: F401
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, WeightedRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .prefetch import DevicePrefetcher  # noqa: F401


class WorkerInfo:
    """Per-worker metadata inside a DataLoader worker process
    (`io/dataloader/worker.py` get_worker_info)."""

    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_WORKER_INFO = [None]


def get_worker_info():
    """None in the main process; a WorkerInfo inside a worker subprocess
    (used by IterableDataset shards to split work)."""
    return _WORKER_INFO[0]
