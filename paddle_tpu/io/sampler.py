"""Samplers + BatchSampler.

Reference parity: `python/paddle/io/` (Sampler, SequenceSampler,
RandomSampler, BatchSampler, DistributedBatchSampler, WeightedRandomSampler).
"""
from __future__ import annotations

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray([float(w) for w in weights])
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space over data-parallel ranks.

    Reference parity: `python/paddle/io/dataloader/dist_batch_sampler.py` —
    on TPU ranks map to processes (multi-host) or to the dp axis of the mesh
    (per-process global batch is sliced by the mesh sharding instead).
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..parallel.env import get_rank, get_world_size
        self.dataset = dataset
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.epoch = 0
        n = len(dataset)
        self.num_samples = (n + self.nranks - 1) // self.nranks if not drop_last \
            else n // self.nranks
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
