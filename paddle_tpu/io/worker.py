"""Multiprocess DataLoader workers with shared-memory numpy transport.

Reference parity: `python/paddle/fluid/dataloader/dataloader_iter.py:1` +
`worker.py:1` (worker processes, shared-memory tensor transport,
out-of-order results re-sequenced) and `operators/reader/buffered_reader.cc`
(double buffering).

TPU-first constraints: workers NEVER touch jax — they produce pure numpy
(device interaction in a forked child of an initialized XLA process is
undefined); the parent does the single H2D hop. Batches cross the process
boundary as `multiprocessing.shared_memory` blocks (zero-copy handoff,
pickle only ships names/shapes), the reference's mmap-backed
`core.Variable` transport re-expressed with the stdlib primitive.
"""
from __future__ import annotations

import multiprocessing as mp
import queue as pyqueue
import threading
import time
from multiprocessing import shared_memory

import numpy as np

from .. import faults as _faults
from .. import monitor as _monitor
from ..core import flags as _flags
from ..utils import syncwatch as _syncwatch

_SENTINEL = None
_DONE = "__worker_done__"   # clean worker exit marker: (_DONE, worker_id)


def _untrack(name):
    """Detach a segment from this process's resource_tracker. The CHILD
    creates segments but the PARENT owns their lifetime (copy-then-unlink);
    without this, the tracker unlinks them when the worker exits — a race
    that manifests as FileNotFoundError on slow consumers (3.12 has no
    track=False yet)."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister("/" + name.lstrip("/"), "shared_memory")
    except Exception:
        pass


def _np_collate(batch):
    """Worker-side collate to NUMPY structures only."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int32)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(_np_collate(list(s)) for s in zip(*batch))
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    raise TypeError(f"multiprocess DataLoader cannot collate {type(sample)}; "
                    "provide a collate_fn returning numpy")


def _to_shm(obj, shms):
    """Replace ndarrays in a nested structure with shm descriptors."""
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        blk = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
        _untrack(blk.name)  # parent owns lifetime, not this process
        view = np.ndarray(arr.shape, arr.dtype, buffer=blk.buf)
        view[...] = arr
        shms.append(blk)
        return ("__shm__", blk.name, arr.shape, arr.dtype.str)
    if isinstance(obj, tuple):
        return tuple(_to_shm(o, shms) for o in obj)
    if isinstance(obj, list):
        return [_to_shm(o, shms) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_shm(v, shms) for k, v in obj.items()}
    return obj


def _from_shm(obj, opened):
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        _, name, shape, dtype = obj
        blk = shared_memory.SharedMemory(name=name)  # attach (no tracker
        opened.append(blk)                           # registration on 3.12)
        # copy out so the block can be unlinked immediately
        return np.ndarray(shape, np.dtype(dtype), buffer=blk.buf).copy()
    if isinstance(obj, tuple):
        return tuple(_from_shm(o, opened) for o in obj)
    if isinstance(obj, list):
        return [_from_shm(o, opened) for o in obj]
    if isinstance(obj, dict):
        return {k: _from_shm(v, opened) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, index_queue, result_queue, collate_fn,
                 use_shared_memory, worker_id, worker_init_fn,
                 num_workers=1, reset_fault_sites=()):
    """Runs in the child process. numpy only — no jax."""
    # A RESPAWNED worker must not inherit the fork-copied worker-kill
    # fault spec that killed its predecessor — it would die forever.
    for site_name in reset_fault_sites:
        _faults.clear_site(site_name)
    # publish worker metadata for get_worker_info (IterableDataset shards)
    try:
        from . import WorkerInfo, _WORKER_INFO
        _WORKER_INFO[0] = WorkerInfo(worker_id, num_workers,
                                     1234 + worker_id, dataset)
    except Exception:
        pass
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    collate = collate_fn or _np_collate
    while True:
        item = index_queue.get()
        if item is _SENTINEL:
            result_queue.put((_DONE, worker_id, None))
            return
        seq, indices = item
        # OUTSIDE the try: an injected fault here escapes the loop and
        # kills the worker PROCESS abruptly (nonzero exit, nothing shipped
        # to the parent) — exactly the failure mode respawn must cover
        if _faults._ENABLED:
            _faults.check("dataloader.worker")
        try:
            batch = collate([dataset[i] for i in indices])
            if use_shared_memory:
                shms = []
                desc = _to_shm(batch, shms)
                result_queue.put((seq, desc, None))
                for blk in shms:  # parent copies out; child just closes
                    blk.close()
            else:
                result_queue.put((seq, batch, None))
        except Exception as e:  # noqa: BLE001 — ship to parent
            import traceback
            result_queue.put((seq, None, f"{e}\n{traceback.format_exc()}"))
            return


class MultiprocessIter:
    """Ordered multiprocess prefetch iterator (dataloader_iter.py role).

    Self-healing: each worker owns a PRIVATE index queue and the parent
    records every (seq, indices) assignment until its batch arrives. A
    worker that dies mid-epoch (OOM-kill, injected fault, segfault) is
    detected by exitcode polling, respawned into a FRESH queue, and its
    outstanding assignments are re-enqueued — the epoch completes with
    every batch exactly once (duplicates a dying worker already shipped
    are dropped by seq), instead of the parent hanging on the result
    queue. Respawns per worker slot are bounded by
    FLAGS_dataloader_max_worker_restarts; past that the death is a hard
    error. Each respawn counts `dataloader.worker_restarts`."""

    _POLL_S = 0.5   # result-queue poll granularity for death detection

    def __init__(self, loader):
        self.loader = loader
        self._ctx = mp.get_context("fork")
        n = loader.num_workers
        self._result_queue = self._ctx.Queue()
        self._index_queues = []
        self._workers = []
        self._pending = {}
        self._emit = 0
        self._seq = 0
        self._n_workers = n
        self._alive = True
        self._timeout = loader.timeout or None
        self._lock = threading.Lock()       # assignments + queue swaps
        self._assigned = [dict() for _ in range(n)]  # wid -> {seq: indices}
        self._finished = [False] * n        # clean sentinel-exit seen
        self._restarts = [0] * n
        self._max_restarts = int(_flags.flag(
            "dataloader_max_worker_restarts"))
        self._feed_done = False
        for wid in range(n):
            self._index_queues.append(self._ctx.Queue())
            self._workers.append(self._spawn(wid))
        self._feeder = _syncwatch.Thread(target=self._feed, daemon=True)
        self._feeder.start()

    def _spawn(self, wid, respawn=False):
        p = self._ctx.Process(
            target=_worker_loop,
            args=(self.loader.dataset, self._index_queues[wid],
                  self._result_queue, self.loader.worker_collate_fn,
                  self.loader.use_shared_memory, wid,
                  self.loader.worker_init_fn, self._n_workers),
            kwargs=dict(reset_fault_sites=("dataloader.worker",)
                        if respawn else ()),
            daemon=True)
        p.start()
        return p

    def _feed(self):
        for indices in self.loader.batch_sampler:
            indices = list(indices)
            wid = self._seq % self._n_workers
            with self._lock:
                self._assigned[wid][self._seq] = indices
                self._index_queues[wid].put((self._seq, indices))
            self._seq += 1
        with self._lock:
            self._feed_done = True
            for q in self._index_queues:
                q.put(_SENTINEL)

    def _respawn_dead_worker(self, wid):
        """Replace a dead worker: fresh index queue seeded with every
        assignment it still owed (the abandoned queue may hold some of
        them too — re-sending all is safe, the parent dedups by seq)."""
        self._restarts[wid] += 1
        if self._restarts[wid] > self._max_restarts:
            self._shutdown()
            raise RuntimeError(
                f"DataLoader worker {wid} died (exitcode "
                f"{self._workers[wid].exitcode}) and exhausted its "
                f"{self._max_restarts} respawns "
                "(FLAGS_dataloader_max_worker_restarts)")
        if _monitor._ENABLED:
            _monitor.count("dataloader.worker_restarts")
        with self._lock:
            self._index_queues[wid] = self._ctx.Queue()
            for seq, indices in sorted(self._assigned[wid].items()):
                self._index_queues[wid].put((seq, indices))
            if self._feed_done:
                self._index_queues[wid].put(_SENTINEL)
        self._workers[wid] = self._spawn(wid, respawn=True)

    def _check_workers(self):
        for wid, p in enumerate(self._workers):
            if p.exitcode is not None and not self._finished[wid]:
                with self._lock:
                    owes = bool(self._assigned[wid]) or not self._feed_done
                if not owes:
                    # died after handing over everything it was assigned
                    # (e.g. killed while idle): nothing to recover
                    self._finished[wid] = True
                    continue
                self._respawn_dead_worker(wid)

    def __next__(self):
        from .. import obs as _obs
        if not _obs._TL_ENABLED:
            return self._next_impl()
        # timeline: consumer-side wait on the worker processes — lands in
        # the NEXT step record's `between` bucket as data_wait
        _t0 = time.time()
        try:
            return self._next_impl()
        finally:
            _t1 = time.time()
            _obs.add_phase("data_wait", _t1 - _t0, _t0, _t1)

    def _next_impl(self):
        deadline = (time.monotonic() + self._timeout) \
            if self._timeout else None
        while True:
            if self._emit in self._pending:
                desc, err = self._pending.pop(self._emit)
                self._emit += 1
                if err is not None:
                    self._shutdown()
                    raise RuntimeError(f"DataLoader worker failed:\n{err}")
                opened = []
                batch = _from_shm(desc, opened) \
                    if self.loader.use_shared_memory else desc
                for blk in opened:
                    blk.close()
                    try:
                        blk.unlink()
                    except FileNotFoundError:
                        pass
                return self.loader._post_collate(batch)
            # epoch complete: every fed batch has been emitted (robust to
            # sentinel loss/duplication across respawns)
            if self._feed_done and self._emit >= self._seq:
                self._shutdown()
                raise StopIteration
            try:
                poll = self._POLL_S
                if deadline is not None:
                    poll = min(poll, max(0.0, deadline - time.monotonic()))
                item = self._result_queue.get(timeout=poll)
            except pyqueue.Empty:
                if deadline is not None and time.monotonic() >= deadline:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader timed out after {self._timeout}s")
                self._check_workers()   # dead worker? respawn + re-enqueue
                continue
            if item[0] == _DONE:   # == : the marker crosses a pickle hop
                self._finished[item[1]] = True
                continue
            seq, desc, err = item
            if seq < self._emit or seq in self._pending:
                # duplicate from a worker that died after shipping (its
                # batches were conservatively re-enqueued): reclaim + drop
                if err is None and self.loader.use_shared_memory:
                    self._unlink_desc(desc)
                continue
            with self._lock:
                self._assigned[seq % self._n_workers].pop(seq, None)
            self._pending[seq] = (desc, err)

    def __iter__(self):
        return self

    @staticmethod
    def _unlink_desc(desc):
        """Reclaim shm segments of an unconsumed batch descriptor (the
        parent owns their lifetime — see _untrack)."""
        if isinstance(desc, tuple) and len(desc) == 4 and desc[0] == "__shm__":
            try:
                blk = shared_memory.SharedMemory(name=desc[1])
                blk.close()
                blk.unlink()
            except FileNotFoundError:
                pass
            return
        if isinstance(desc, (tuple, list)):
            for o in desc:
                MultiprocessIter._unlink_desc(o)
        elif isinstance(desc, dict):
            for o in desc.values():
                MultiprocessIter._unlink_desc(o)

    def _shutdown(self):
        if not self._alive:
            return
        self._alive = False
        for p in self._workers:
            if p.is_alive():
                p.terminate()
        for p in self._workers:
            p.join(timeout=2)
        # early exit (break / exception / GC): prefetched-but-unconsumed
        # batches still hold untracked shm segments — unlink them here
        if self.loader.use_shared_memory:
            for desc, _err in self._pending.values():
                self._unlink_desc(desc)
            self._pending.clear()
            while True:
                try:
                    item = self._result_queue.get_nowait()
                except (pyqueue.Empty, OSError, ValueError):
                    break
                if (item is not _SENTINEL and item[0] != _DONE
                        and item[2] is None):
                    self._unlink_desc(item[1])

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass
