"""Multiprocess DataLoader workers with shared-memory numpy transport.

Reference parity: `python/paddle/fluid/dataloader/dataloader_iter.py:1` +
`worker.py:1` (worker processes, shared-memory tensor transport,
out-of-order results re-sequenced) and `operators/reader/buffered_reader.cc`
(double buffering).

TPU-first constraints: workers NEVER touch jax — they produce pure numpy
(device interaction in a forked child of an initialized XLA process is
undefined); the parent does the single H2D hop. Batches cross the process
boundary as `multiprocessing.shared_memory` blocks (zero-copy handoff,
pickle only ships names/shapes), the reference's mmap-backed
`core.Variable` transport re-expressed with the stdlib primitive.
"""
from __future__ import annotations

import multiprocessing as mp
import queue as pyqueue
import threading
from multiprocessing import shared_memory

import numpy as np

_SENTINEL = None


def _untrack(name):
    """Detach a segment from this process's resource_tracker. The CHILD
    creates segments but the PARENT owns their lifetime (copy-then-unlink);
    without this, the tracker unlinks them when the worker exits — a race
    that manifests as FileNotFoundError on slow consumers (3.12 has no
    track=False yet)."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister("/" + name.lstrip("/"), "shared_memory")
    except Exception:
        pass


def _np_collate(batch):
    """Worker-side collate to NUMPY structures only."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int32)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(_np_collate(list(s)) for s in zip(*batch))
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    raise TypeError(f"multiprocess DataLoader cannot collate {type(sample)}; "
                    "provide a collate_fn returning numpy")


def _to_shm(obj, shms):
    """Replace ndarrays in a nested structure with shm descriptors."""
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        blk = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
        _untrack(blk.name)  # parent owns lifetime, not this process
        view = np.ndarray(arr.shape, arr.dtype, buffer=blk.buf)
        view[...] = arr
        shms.append(blk)
        return ("__shm__", blk.name, arr.shape, arr.dtype.str)
    if isinstance(obj, tuple):
        return tuple(_to_shm(o, shms) for o in obj)
    if isinstance(obj, list):
        return [_to_shm(o, shms) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_shm(v, shms) for k, v in obj.items()}
    return obj


def _from_shm(obj, opened):
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        _, name, shape, dtype = obj
        blk = shared_memory.SharedMemory(name=name)  # attach (no tracker
        opened.append(blk)                           # registration on 3.12)
        # copy out so the block can be unlinked immediately
        return np.ndarray(shape, np.dtype(dtype), buffer=blk.buf).copy()
    if isinstance(obj, tuple):
        return tuple(_from_shm(o, opened) for o in obj)
    if isinstance(obj, list):
        return [_from_shm(o, opened) for o in obj]
    if isinstance(obj, dict):
        return {k: _from_shm(v, opened) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, index_queue, result_queue, collate_fn,
                 use_shared_memory, worker_id, worker_init_fn,
                 num_workers=1):
    """Runs in the child process. numpy only — no jax."""
    # publish worker metadata for get_worker_info (IterableDataset shards)
    try:
        from . import WorkerInfo, _WORKER_INFO
        _WORKER_INFO[0] = WorkerInfo(worker_id, num_workers,
                                     1234 + worker_id, dataset)
    except Exception:
        pass
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    collate = collate_fn or _np_collate
    while True:
        item = index_queue.get()
        if item is _SENTINEL:
            result_queue.put(_SENTINEL)
            return
        seq, indices = item
        try:
            batch = collate([dataset[i] for i in indices])
            if use_shared_memory:
                shms = []
                desc = _to_shm(batch, shms)
                result_queue.put((seq, desc, None))
                for blk in shms:  # parent copies out; child just closes
                    blk.close()
            else:
                result_queue.put((seq, batch, None))
        except Exception as e:  # noqa: BLE001 — ship to parent
            import traceback
            result_queue.put((seq, None, f"{e}\n{traceback.format_exc()}"))
            return


class MultiprocessIter:
    """Ordered multiprocess prefetch iterator (dataloader_iter.py role)."""

    def __init__(self, loader):
        self.loader = loader
        ctx = mp.get_context("fork")
        n = loader.num_workers
        self._index_queue = ctx.Queue()
        self._result_queue = ctx.Queue()
        self._workers = []
        self._pending = {}
        self._emit = 0
        self._seq = 0
        self._done_workers = 0
        self._n_workers = n
        self._alive = True
        self._timeout = loader.timeout or None
        for wid in range(n):
            p = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self._index_queue, self._result_queue,
                      loader.worker_collate_fn, loader.use_shared_memory, wid,
                      loader.worker_init_fn, n),
                daemon=True)
            p.start()
            self._workers.append(p)
        self._feeder = threading.Thread(target=self._feed, daemon=True)
        self._feeder.start()

    def _feed(self):
        for indices in self.loader.batch_sampler:
            self._index_queue.put((self._seq, list(indices)))
            self._seq += 1
        for _ in range(self._n_workers):
            self._index_queue.put(_SENTINEL)

    def __next__(self):
        while True:
            if self._emit in self._pending:
                desc, err = self._pending.pop(self._emit)
                self._emit += 1
                if err is not None:
                    self._shutdown()
                    raise RuntimeError(f"DataLoader worker failed:\n{err}")
                opened = []
                batch = _from_shm(desc, opened) \
                    if self.loader.use_shared_memory else desc
                for blk in opened:
                    blk.close()
                    try:
                        blk.unlink()
                    except FileNotFoundError:
                        pass
                return self.loader._post_collate(batch)
            if self._done_workers >= self._n_workers:
                if self._emit in self._pending:
                    continue
                self._shutdown()
                raise StopIteration
            try:
                item = self._result_queue.get(timeout=self._timeout)
            except pyqueue.Empty:
                self._shutdown()
                raise RuntimeError(
                    f"DataLoader timed out after {self._timeout}s")
            if item is _SENTINEL:
                self._done_workers += 1
                continue
            seq, desc, err = item
            self._pending[seq] = (desc, err)

    def __iter__(self):
        return self

    @staticmethod
    def _unlink_desc(desc):
        """Reclaim shm segments of an unconsumed batch descriptor (the
        parent owns their lifetime — see _untrack)."""
        if isinstance(desc, tuple) and len(desc) == 4 and desc[0] == "__shm__":
            try:
                blk = shared_memory.SharedMemory(name=desc[1])
                blk.close()
                blk.unlink()
            except FileNotFoundError:
                pass
            return
        if isinstance(desc, (tuple, list)):
            for o in desc:
                MultiprocessIter._unlink_desc(o)
        elif isinstance(desc, dict):
            for o in desc.values():
                MultiprocessIter._unlink_desc(o)

    def _shutdown(self):
        if not self._alive:
            return
        self._alive = False
        for p in self._workers:
            if p.is_alive():
                p.terminate()
        for p in self._workers:
            p.join(timeout=2)
        # early exit (break / exception / GC): prefetched-but-unconsumed
        # batches still hold untracked shm segments — unlink them here
        if self.loader.use_shared_memory:
            for desc, _err in self._pending.values():
                self._unlink_desc(desc)
            self._pending.clear()
            while True:
                try:
                    item = self._result_queue.get_nowait()
                except (pyqueue.Empty, OSError, ValueError):
                    break
                if item is not _SENTINEL and item[2] is None:
                    self._unlink_desc(item[1])

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass
