"""paddle.vision.ops parity: detection ops (nms, box coding, roi pooling,
yolo utilities). Reference parity: `paddle/fluid/operators/detection/`.
Dynamic-size outputs (nms keep-lists) host-sync, as on any accelerator.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    b = ensure_tensor(boxes).numpy()
    s = ensure_tensor(scores).numpy() if scores is not None else np.ones(len(b), "float32")
    order = np.argsort(-s)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        rest = order[1:]
        xx1 = np.maximum(b[i, 0], b[rest, 0])
        yy1 = np.maximum(b[i, 1], b[rest, 1])
        xx2 = np.minimum(b[i, 2], b[rest, 2])
        yy2 = np.minimum(b[i, 3], b[rest, 3])
        inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
        a_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        a_r = (b[rest, 2] - b[rest, 0]) * (b[rest, 3] - b[rest, 1])
        iou = inter / np.maximum(a_i + a_r - inter, 1e-9)
        order = rest[iou <= iou_threshold]
    keep = np.asarray(keep[:top_k] if top_k else keep, dtype="int64")
    return Tensor(jnp.asarray(keep))


def box_iou(boxes1, boxes2):
    b1, b2 = ensure_tensor(boxes1), ensure_tensor(boxes2)

    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None] - inter, 1e-9)

    return run_op(f, [b1, b2], "box_iou")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLO head output [N, A*(5+C), H, W] -> boxes + scores."""
    x = ensure_tensor(x)
    na = len(anchors) // 2
    anc = np.asarray(anchors, dtype="float32").reshape(na, 2)

    def f(a):
        n, _, h, w = a.shape
        a = a.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=a.dtype)
        gy = jnp.arange(h, dtype=a.dtype)
        cx = (jax_sigmoid(a[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx[None, None, None, :]) / w
        cy = (jax_sigmoid(a[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy[None, None, :, None]) / h
        bw = jnp.exp(a[:, :, 2]) * anc[None, :, 0, None, None] / (w * downsample_ratio)
        bh = jnp.exp(a[:, :, 3]) * anc[None, :, 1, None, None] / (h * downsample_ratio)
        obj = jax_sigmoid(a[:, :, 4])
        cls = jax_sigmoid(a[:, :, 5:])
        scores = obj[:, :, None] * cls
        x1, y1 = cx - bw / 2, cy - bh / 2
        x2, y2 = cx + bw / 2, cy + bh / 2
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [n,na,h,w,4]
        boxes = boxes.reshape(n, -1, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        if clip_bbox:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        return boxes, scores

    import jax
    jax_sigmoid = jax.nn.sigmoid
    return run_op(f, [x], "yolo_box")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=True):
    """RoIAlign via bilinear sampling (jax.scipy map_coordinates)."""
    import jax
    x = ensure_tensor(x)
    b = ensure_tensor(boxes)._value
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size

    def f(feat):
        n, c, h, w = feat.shape
        outs = []
        off = 0.5 if aligned else 0.0
        for i in range(b.shape[0]):
            x1, y1, x2, y2 = b[i] * spatial_scale - off
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            coords = jnp.stack([gy.reshape(-1), gx.reshape(-1)])
            sampled = jax.vmap(
                lambda ch: jax.scipy.ndimage.map_coordinates(ch, coords, order=1))(feat[0])
            outs.append(sampled.reshape(c, oh, ow))
        return jnp.stack(outs)

    return run_op(f, [x], "roi_align")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (`python/paddle/vision/ops.py:423` over
    the deformable_conv op). x [N,Cin,H,W]; offset
    [N, 2*dg*kh*kw, Ho, Wo] interleaved (dy, dx) per kernel position; mask
    [N, dg*kh*kw, Ho, Wo] enables the v2 modulated form.

    TPU design: a gather problem, not a conv problem — for each of the
    kh*kw kernel taps (static python loop) the learned offsets produce one
    bilinear 4-corner gather over the image, vectorized across N x dg x
    Ho x Wo; the sampled column tensor then contracts with the weights in
    ONE grouped einsum on the MXU. No scalar loops, no dynamic shapes.
    """
    import jax
    x, offset, weight = ensure_tensor(x), ensure_tensor(offset), ensure_tensor(weight)
    mask_t = ensure_tensor(mask) if mask is not None else None
    bias_t = ensure_tensor(bias) if bias is not None else None
    to2 = lambda v: (v, v) if isinstance(v, int) else tuple(v)
    sh, sw = to2(stride)
    ph, pw = to2(padding)
    dh, dw = to2(dilation)

    def f(xa, off, wt, *rest):
        ms = rest[0] if mask_t is not None else None
        N, Cin, H, W = xa.shape
        Cout, Cg, kh, kw = wt.shape
        dg = deformable_groups
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        want_off = (N, 2 * dg * kh * kw, Ho, Wo)
        if tuple(off.shape) != want_off:
            from ..core.enforce import InvalidArgumentError
            raise InvalidArgumentError(
                f"deform_conv2d: offset shape {tuple(off.shape)} != "
                f"expected {want_off} (2*deformable_groups*kh*kw offsets "
                "per output position)")
        off = off.reshape(N, dg, kh * kw, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - ph)[:, None]          # [Ho,1]
        base_x = (jnp.arange(Wo) * sw - pw)[None, :]          # [1,Wo]
        xg = xa.reshape(N, dg, Cin // dg, H, W)

        cols = []
        for t in range(kh * kw):
            i, j = t // kw, t % kw
            fy = base_y + i * dh + off[:, :, t, 0]            # [N,dg,Ho,Wo]
            fx = base_x + j * dw + off[:, :, t, 1]

            def samp(img, yy, xx):
                """img [Cg,H,W], yy/xx [P] -> [Cg,P] zero-padded bilinear."""
                imgf = img.reshape(img.shape[0], H * W)
                y0 = jnp.floor(yy)
                x0 = jnp.floor(xx)
                wy = yy - y0
                wx = xx - x0
                y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
                out = jnp.zeros((img.shape[0], yy.shape[0]), img.dtype)
                for ddy in (0, 1):
                    for ddx in (0, 1):
                        iy, ix = y0i + ddy, x0i + ddx
                        wgt = (wy if ddy else 1 - wy) * (wx if ddx else 1 - wx)
                        ok = (iy >= 0) & (iy < H) & (ix >= 0) & (ix < W)
                        v = jnp.take(imgf, jnp.clip(iy, 0, H - 1) * W
                                     + jnp.clip(ix, 0, W - 1), axis=1)
                        out = out + v * jnp.where(ok, wgt, 0.0)[None]
                return out

            s = jax.vmap(jax.vmap(samp))(
                xg, fy.reshape(N, dg, -1), fx.reshape(N, dg, -1))
            if ms is not None:
                s = s * ms.reshape(
                    N, dg, kh * kw, Ho * Wo)[:, :, t][:, :, None, :]
            cols.append(s)                                    # [N,dg,Cg',P]
        cols = jnp.stack(cols, axis=3)          # [N, dg, Cin/dg, khkw, P]
        cols = cols.reshape(N, Cin, kh * kw, Ho * Wo)
        g = groups
        cols = cols.reshape(N, g, Cin // g, kh * kw, Ho * Wo)
        wt_g = wt.reshape(g, Cout // g, Cg, kh * kw)
        out = jnp.einsum("ngckp,gock->ngop", cols, wt_g)
        out = out.reshape(N, Cout, Ho, Wo)
        if bias_t is not None:
            out = out + rest[-1].reshape(1, Cout, 1, 1)
        return out

    extra = ([mask_t] if mask_t is not None else []) + \
        ([bias_t] if bias_t is not None else [])
    return run_op(f, [x, offset, weight, *extra], "deform_conv2d")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Quantized max RoI pooling (`python/paddle/vision/ops.py:1022` over
    roi_pool_op: rounded box corners, ceil/floor bin edges, empty bin -> 0).
    Masked-max formulation: per (roi, bin) a row/col membership mask over
    the feature map drives one max reduction — jit-safe, no dynamic shapes.
    """
    x = ensure_tensor(x)
    b = ensure_tensor(boxes)
    bn = np.asarray(ensure_tensor(boxes_num)._value).astype(np.int64) \
        if boxes_num is not None else None
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def f(feat, bx):
        N, C, H, W = feat.shape
        K = bx.shape[0]
        img_of_roi = np.zeros(K, np.int32)
        if bn is not None:
            img_of_roi = np.repeat(np.arange(len(bn)), bn).astype(np.int32)
        rs_w = jnp.round(bx[:, 0] * spatial_scale).astype(jnp.int32)
        rs_h = jnp.round(bx[:, 1] * spatial_scale).astype(jnp.int32)
        re_w = jnp.round(bx[:, 2] * spatial_scale).astype(jnp.int32)
        re_h = jnp.round(bx[:, 3] * spatial_scale).astype(jnp.int32)
        roi_w = jnp.maximum(re_w - rs_w + 1, 1)
        roi_h = jnp.maximum(re_h - rs_h + 1, 1)
        bin_h = roi_h.astype(jnp.float32) / oh
        bin_w = roi_w.astype(jnp.float32) / ow
        phs = jnp.arange(oh)[None, :]
        pws = jnp.arange(ow)[None, :]
        hstart = jnp.clip(jnp.floor(phs * bin_h[:, None]).astype(jnp.int32)
                          + rs_h[:, None], 0, H)
        hend = jnp.clip(jnp.ceil((phs + 1) * bin_h[:, None]).astype(jnp.int32)
                        + rs_h[:, None], 0, H)
        wstart = jnp.clip(jnp.floor(pws * bin_w[:, None]).astype(jnp.int32)
                          + rs_w[:, None], 0, W)
        wend = jnp.clip(jnp.ceil((pws + 1) * bin_w[:, None]).astype(jnp.int32)
                        + rs_w[:, None], 0, W)
        rows = jnp.arange(H)
        cols = jnp.arange(W)
        fk = feat[img_of_roi]                                # [K,C,H,W]
        neg = jnp.asarray(-jnp.inf, feat.dtype)
        # one masked reduce per (ph, pw) bin — static oh*ow loop keeps the
        # peak intermediate at [K,C,H,W] (XLA fuses the select into the
        # reduce), instead of a [K,C,oh,ow,H,W] broadcast
        bins = []
        for ph in range(oh):
            rmask = (rows[None] >= hstart[:, ph, None]) \
                & (rows[None] < hend[:, ph, None])           # [K,H]
            for pw2 in range(ow):
                cmask = (cols[None] >= wstart[:, pw2, None]) \
                    & (cols[None] < wend[:, pw2, None])      # [K,W]
                m = rmask[:, :, None] & cmask[:, None, :]    # [K,H,W]
                v = jnp.where(m[:, None], fk, neg).max(axis=(-2, -1))
                bins.append(jnp.where(m.any(axis=(-2, -1))[:, None], v, 0.0))
        out = jnp.stack(bins, axis=-1)                       # [K,C,oh*ow]
        return out.reshape(out.shape[0], out.shape[1], oh, ow)

    return run_op(f, [x, b], "roi_pool")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (R-FCN;
    `python/paddle/vision/ops.py:911` over psroi_pool_op). Input channels
    C = out_c * oh * ow; output bin (c, ph, pw) averages input channel
    c*oh*ow + ph*ow + pw over the bin; empty bins -> 0."""
    x = ensure_tensor(x)
    b = ensure_tensor(boxes)
    bn = np.asarray(ensure_tensor(boxes_num)._value).astype(np.int64) \
        if boxes_num is not None else None
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def f(feat, bx):
        N, C, H, W = feat.shape
        out_c = C // (oh * ow)
        K = bx.shape[0]
        img_of_roi = np.zeros(K, np.int32)
        if bn is not None:
            img_of_roi = np.repeat(np.arange(len(bn)), bn).astype(np.int32)
        # psroi uses un-rounded scaled coords (psroi_pool_op contract:
        # start rounded-down, end rounded-up to grid, min size 0.1)
        rs_w = jnp.round(bx[:, 0]) * spatial_scale
        rs_h = jnp.round(bx[:, 1]) * spatial_scale
        re_w = jnp.round(bx[:, 2] + 1.0) * spatial_scale
        re_h = jnp.round(bx[:, 3] + 1.0) * spatial_scale
        roi_h = jnp.maximum(re_h - rs_h, 0.1)
        roi_w = jnp.maximum(re_w - rs_w, 0.1)
        bin_h = roi_h / oh
        bin_w = roi_w / ow
        phs = jnp.arange(oh)[None, :]
        pws = jnp.arange(ow)[None, :]
        hstart = jnp.clip(jnp.floor(phs * bin_h[:, None] + rs_h[:, None])
                          .astype(jnp.int32), 0, H)
        hend = jnp.clip(jnp.ceil((phs + 1) * bin_h[:, None] + rs_h[:, None])
                        .astype(jnp.int32), 0, H)
        wstart = jnp.clip(jnp.floor(pws * bin_w[:, None] + rs_w[:, None])
                          .astype(jnp.int32), 0, W)
        wend = jnp.clip(jnp.ceil((pws + 1) * bin_w[:, None] + rs_w[:, None])
                        .astype(jnp.int32), 0, W)
        rows = jnp.arange(H)
        cols = jnp.arange(W)
        fk = feat[img_of_roi].reshape(K, out_c, oh, ow, H, W)
        # static per-bin loop (see roi_pool): position-sensitive channel
        # slice per bin, masked mean, peak intermediate [K,out_c,H,W]
        bins = []
        for ph in range(oh):
            rmask = (rows[None] >= hstart[:, ph, None]) \
                & (rows[None] < hend[:, ph, None])
            for pw2 in range(ow):
                cmask = (cols[None] >= wstart[:, pw2, None]) \
                    & (cols[None] < wend[:, pw2, None])
                m = (rmask[:, :, None] & cmask[:, None, :]).astype(feat.dtype)
                ssum = (fk[:, :, ph, pw2] * m[:, None]).sum(axis=(-2, -1))
                cnt = m.sum(axis=(-2, -1))[:, None]
                bins.append(jnp.where(cnt > 0, ssum / jnp.maximum(cnt, 1.0),
                                      0.0))
        out = jnp.stack(bins, axis=-1)
        return out.reshape(K, out_c, oh, ow)

    return run_op(f, [x, b], "psroi_pool")


def _sce(x, z):
    """Numerically-stable sigmoid cross-entropy (yolov3_loss_op.h
    SigmoidCrossEntropy contract)."""
    return jnp.maximum(x, 0.0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (`python/paddle/vision/ops.py:42` over
    yolov3_loss_op.h). x [N, mask_num*(5+C), H, W]; gt_box [N, B, 4]
    normalized (cx, cy, w, h); gt_label [N, B]; returns per-image loss [N].

    Semantics follow the reference kernel: per-cell best-IoU > ignore_thresh
    suppresses the negative objectness term; each gt matches its best
    anchor by (w, h) IoU; positives take sigmoid-CE x/y + L1 w/h location
    loss scaled by (2 - w*h) * score, objectness CE with score target, and
    per-class sigmoid CE with optional label smoothing. The whole thing is
    masked dense algebra (one [N,M,HW,B] IoU tensor, scatters for the
    positive cells) — fully differentiable by jax, matching the
    hand-written CUDA gradients up to the L1 subgradient at 0.
    """
    x = ensure_tensor(x)
    gb_t, gl_t = ensure_tensor(gt_box), ensure_tensor(gt_label)
    gs_t = ensure_tensor(gt_score) if gt_score is not None else None
    anchors = list(anchors)
    anchor_mask = list(anchor_mask)

    def f(xa, gb, gl, *rest):
        N, _, H, W = xa.shape
        B = gb.shape[1]
        M = len(anchor_mask)
        an_num = len(anchors) // 2
        C = class_num
        gs = rest[0] if gs_t is not None else jnp.ones((N, B), xa.dtype)
        xa5 = xa.reshape(N, M, 5 + C, H, W)
        input_size = downsample_ratio * H
        bias = -0.5 * (scale_x_y - 1.0)
        sig = jax.nn.sigmoid

        # --- predicted boxes (reference divides BOTH axes by grid h) ---
        ii = jnp.arange(W, dtype=xa.dtype)
        jj = jnp.arange(H, dtype=xa.dtype)
        px = (ii[None, None, None, :] + sig(xa5[:, :, 0]) * scale_x_y + bias) / H
        py = (jj[None, None, :, None] + sig(xa5[:, :, 1]) * scale_x_y + bias) / H
        anc = jnp.asarray(anchors, xa.dtype).reshape(an_num, 2)
        anc_m = anc[jnp.asarray(anchor_mask)]
        pw = jnp.exp(xa5[:, :, 2]) * anc_m[:, 0][None, :, None, None] / input_size
        ph = jnp.exp(xa5[:, :, 3]) * anc_m[:, 1][None, :, None, None] / input_size

        valid = (gb[:, :, 2] > 1e-6) & (gb[:, :, 3] > 1e-6)   # [N,B]

        def overlap(c1, w1, c2, w2):
            return jnp.minimum(c1 + w1 / 2, c2 + w2 / 2) \
                - jnp.maximum(c1 - w1 / 2, c2 - w2 / 2)

        # --- per-cell best IoU vs gts -> ignore mask ---
        P = H * W
        pxf = px.reshape(N, M, P, 1)
        pyf = py.reshape(N, M, P, 1)
        pwf = pw.reshape(N, M, P, 1)
        phf = ph.reshape(N, M, P, 1)
        gx = gb[:, None, None, :, 0]
        gy = gb[:, None, None, :, 1]
        gw = gb[:, None, None, :, 2]
        gh = gb[:, None, None, :, 3]
        ow_ = overlap(pxf, pwf, gx, gw)
        oh_ = overlap(pyf, phf, gy, gh)
        inter = jnp.where((ow_ > 0) & (oh_ > 0), ow_ * oh_, 0.0)
        union = pwf * phf + gw * gh - inter
        iou = jnp.where(valid[:, None, None, :], inter / jnp.maximum(union, 1e-10), 0.0)
        best_iou = iou.max(-1)                                # [N,M,P]
        ignore = best_iou > ignore_thresh

        # --- per-gt best anchor by (w,h) IoU over ALL anchors ---
        aw = anc[:, 0] / input_size                           # [A]
        ah = anc[:, 1] / input_size
        gwb = gb[:, :, 2][:, :, None]
        ghb = gb[:, :, 3][:, :, None]
        inter_a = jnp.minimum(gwb, aw[None, None]) * jnp.minimum(ghb, ah[None, None])
        union_a = gwb * ghb + aw[None, None] * ah[None, None] - inter_a
        iou_a = inter_a / jnp.maximum(union_a, 1e-10)
        best_n = jnp.argmax(iou_a, axis=-1)                   # [N,B]
        mask_lut = -np.ones(an_num, np.int32)
        for mi, a in enumerate(anchor_mask):
            mask_lut[a] = mi
        mask_idx = jnp.asarray(mask_lut)[best_n]              # [N,B]
        matched = valid & (mask_idx >= 0)

        gi = jnp.clip((gb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)

        # --- gather predictions at positive cells ---
        mi_safe = jnp.maximum(mask_idx, 0)
        nb = jnp.broadcast_to(jnp.arange(N)[:, None], (N, B))
        sel = xa5[nb, mi_safe, :, gj, gi]                     # [N,B,5+C]
        ttx = gb[:, :, 0] * W - gi
        tty = gb[:, :, 1] * H - gj
        anw = anc[:, 0][best_n]
        anh = anc[:, 1][best_n]
        ttw = jnp.log(jnp.maximum(gb[:, :, 2] * input_size / anw, 1e-9))
        tth = jnp.log(jnp.maximum(gb[:, :, 3] * input_size / anh, 1e-9))
        loc_scale = (2.0 - gb[:, :, 2] * gb[:, :, 3]) * gs
        loc = (_sce(sel[:, :, 0], ttx) + _sce(sel[:, :, 1], tty)
               + jnp.abs(sel[:, :, 2] - ttw) + jnp.abs(sel[:, :, 3] - tth)) \
            * loc_scale
        loc = jnp.where(matched, loc, 0.0)

        if use_label_smooth:
            sw = min(1.0 / max(C, 1), 1.0 / 40)
            pos_l, neg_l = 1.0 - sw, sw
        else:
            pos_l, neg_l = 1.0, 0.0
        cls_ids = jnp.arange(C)
        tgt = jnp.where(cls_ids[None, None, :] == gl[:, :, None], pos_l, neg_l)
        cls = (_sce(sel[:, :, 5:], tgt).sum(-1)) * gs
        cls = jnp.where(matched, cls, 0.0)

        # --- objectness mask: 0 neg, -1 ignored, score at positives ---
        obj = jnp.where(ignore, -1.0, 0.0)                    # [N,M,P]
        pidx = gj * W + gi
        mi_scatter = jnp.where(matched, mi_safe, M)           # OOB -> dropped
        obj = obj.at[nb, mi_scatter, pidx].set(
            gs.astype(obj.dtype), mode="drop")
        tobj = xa5[:, :, 4].reshape(N, M, P)
        obj_loss = jnp.where(
            obj > 1e-5, _sce(tobj, 1.0) * obj,
            jnp.where(obj > -0.5, _sce(tobj, 0.0), 0.0))

        per_image = loc.sum(-1) + cls.sum(-1) \
            + obj_loss.sum(axis=(1, 2))
        return per_image

    import jax
    extra = [gs_t] if gs_t is not None else []
    return run_op(f, [x, gb_t, gl_t, *extra], "yolo_loss")


def read_file(filename, name=None):
    """Read raw file bytes as a uint8 tensor (`python/paddle/vision/ops.py`
    read_file)."""
    with open(filename, "rb") as fh:
        data = np.frombuffer(fh.read(), dtype=np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to [C,H,W] uint8 (host-side via PIL — the
    TPU has no image codec unit; the reference decodes on CPU/nvjpeg too)."""
    import io
    from PIL import Image
    data = bytes(np.asarray(ensure_tensor(x)._value).astype(np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def _layer_base():
    from ..nn import Layer
    return Layer


def _define_layers():
    """Layer wrappers defined lazily (vision.ops imports before nn)."""
    Layer = _layer_base()

    class DeformConv2D(Layer):
        """paddle.vision.ops.DeformConv2D (`vision/ops.py:423` layer)."""

        def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                     padding=0, dilation=1, deformable_groups=1, groups=1,
                     weight_attr=None, bias_attr=None):
            super().__init__()
            from ..nn import initializer
            kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
                else tuple(kernel_size)
            self._stride, self._padding, self._dilation = stride, padding, dilation
            self._dg, self._groups = deformable_groups, groups
            import math as _m
            k = 1.0 / _m.sqrt(in_channels * kh * kw)
            self.weight = self.create_parameter(
                (out_channels, in_channels // groups, kh, kw),
                default_initializer=initializer.Uniform(-k, k))
            self.bias = None if bias_attr is False else self.create_parameter(
                (out_channels,), default_initializer=initializer.Constant(0.0))

        def forward(self, x, offset, mask=None):
            return deform_conv2d(x, offset, self.weight, self.bias,
                                 self._stride, self._padding, self._dilation,
                                 self._dg, self._groups, mask)

    class RoIPool(Layer):
        def __init__(self, output_size, spatial_scale=1.0):
            super().__init__()
            self._os, self._ss = output_size, spatial_scale

        def forward(self, x, boxes, boxes_num):
            return roi_pool(x, boxes, boxes_num, self._os, self._ss)

    class PSRoIPool(Layer):
        def __init__(self, output_size, spatial_scale=1.0):
            super().__init__()
            self._os, self._ss = output_size, spatial_scale

        def forward(self, x, boxes, boxes_num):
            return psroi_pool(x, boxes, boxes_num, self._os, self._ss)

    class RoIAlign(Layer):
        def __init__(self, output_size, spatial_scale=1.0):
            super().__init__()
            self._os, self._ss = output_size, spatial_scale

        def forward(self, x, boxes, boxes_num):
            return roi_align(x, boxes, boxes_num, self._os, self._ss)

    return DeformConv2D, RoIPool, PSRoIPool, RoIAlign


def __getattr__(name):
    if name in ("DeformConv2D", "RoIPool", "PSRoIPool", "RoIAlign"):
        import sys
        mod = sys.modules[__name__]
        (mod.DeformConv2D, mod.RoIPool, mod.PSRoIPool,
         mod.RoIAlign) = _define_layers()
        return getattr(mod, name)
    raise AttributeError(f"module 'paddle_tpu.vision.ops' has no attribute {name!r}")
