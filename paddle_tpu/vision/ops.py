"""paddle.vision.ops parity: detection ops (nms, box coding, roi pooling,
yolo utilities). Reference parity: `paddle/fluid/operators/detection/`.
Dynamic-size outputs (nms keep-lists) host-sync, as on any accelerator.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    b = ensure_tensor(boxes).numpy()
    s = ensure_tensor(scores).numpy() if scores is not None else np.ones(len(b), "float32")
    order = np.argsort(-s)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        rest = order[1:]
        xx1 = np.maximum(b[i, 0], b[rest, 0])
        yy1 = np.maximum(b[i, 1], b[rest, 1])
        xx2 = np.minimum(b[i, 2], b[rest, 2])
        yy2 = np.minimum(b[i, 3], b[rest, 3])
        inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
        a_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        a_r = (b[rest, 2] - b[rest, 0]) * (b[rest, 3] - b[rest, 1])
        iou = inter / np.maximum(a_i + a_r - inter, 1e-9)
        order = rest[iou <= iou_threshold]
    keep = np.asarray(keep[:top_k] if top_k else keep, dtype="int64")
    return Tensor(jnp.asarray(keep))


def box_iou(boxes1, boxes2):
    b1, b2 = ensure_tensor(boxes1), ensure_tensor(boxes2)

    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None] - inter, 1e-9)

    return run_op(f, [b1, b2], "box_iou")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLO head output [N, A*(5+C), H, W] -> boxes + scores."""
    x = ensure_tensor(x)
    na = len(anchors) // 2
    anc = np.asarray(anchors, dtype="float32").reshape(na, 2)

    def f(a):
        n, _, h, w = a.shape
        a = a.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=a.dtype)
        gy = jnp.arange(h, dtype=a.dtype)
        cx = (jax_sigmoid(a[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx[None, None, None, :]) / w
        cy = (jax_sigmoid(a[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy[None, None, :, None]) / h
        bw = jnp.exp(a[:, :, 2]) * anc[None, :, 0, None, None] / (w * downsample_ratio)
        bh = jnp.exp(a[:, :, 3]) * anc[None, :, 1, None, None] / (h * downsample_ratio)
        obj = jax_sigmoid(a[:, :, 4])
        cls = jax_sigmoid(a[:, :, 5:])
        scores = obj[:, :, None] * cls
        x1, y1 = cx - bw / 2, cy - bh / 2
        x2, y2 = cx + bw / 2, cy + bh / 2
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [n,na,h,w,4]
        boxes = boxes.reshape(n, -1, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        if clip_bbox:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        return boxes, scores

    import jax
    jax_sigmoid = jax.nn.sigmoid
    return run_op(f, [x], "yolo_box")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=True):
    """RoIAlign via bilinear sampling (jax.scipy map_coordinates)."""
    import jax
    x = ensure_tensor(x)
    b = ensure_tensor(boxes)._value
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size

    def f(feat):
        n, c, h, w = feat.shape
        outs = []
        off = 0.5 if aligned else 0.0
        for i in range(b.shape[0]):
            x1, y1, x2, y2 = b[i] * spatial_scale - off
            ys = y1 + (jnp.arange(oh) + 0.5) * (y2 - y1) / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * (x2 - x1) / ow
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            coords = jnp.stack([gy.reshape(-1), gx.reshape(-1)])
            sampled = jax.vmap(
                lambda ch: jax.scipy.ndimage.map_coordinates(ch, coords, order=1))(feat[0])
            outs.append(sampled.reshape(c, oh, ow))
        return jnp.stack(outs)

    return run_op(f, [x], "roi_align")


def deform_conv2d(*a, **kw):
    raise NotImplementedError("deform_conv2d: planned (round 2)")
