"""paddle.vision.models parity — re-exports from paddle_tpu.models."""
from ...models import (  # noqa: F401
    LeNet, MobileNetV1, MobileNetV2, ResNet, VGG, mobilenet_v1, mobilenet_v2,
    resnet18, resnet34, resnet50, resnet101, resnet152, vgg11, vgg13, vgg16, vgg19,
    wide_resnet50_2, wide_resnet101_2,
)
from ...models import (  # noqa: F401
    AlexNet, DenseNet, GoogLeNet, InceptionV3, ResNeXt, ShuffleNetV2,
    SqueezeNet, alexnet, densenet121, densenet161, densenet169, densenet201,
    densenet264, googlenet, inception_v3, resnext50_32x4d, resnext50_64x4d,
    resnext101_32x4d, resnext101_64x4d, resnext152_32x4d, resnext152_64x4d,
    shufflenet_v2_x0_25, shufflenet_v2_x0_33, shufflenet_v2_x0_5,
    shufflenet_v2_x1_0, shufflenet_v2_x1_5, shufflenet_v2_x2_0,
    shufflenet_v2_swish, squeezenet1_0, squeezenet1_1,
)
