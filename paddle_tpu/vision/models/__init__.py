"""paddle.vision.models parity — re-exports from paddle_tpu.models."""
from ...models import (  # noqa: F401
    LeNet, MobileNetV1, MobileNetV2, ResNet, VGG, mobilenet_v1, mobilenet_v2,
    resnet18, resnet34, resnet50, resnet101, resnet152, vgg11, vgg13, vgg16, vgg19,
    wide_resnet50_2, wide_resnet101_2,
)
from ...models import (  # noqa: F401
    AlexNet, DenseNet, GoogLeNet, ShuffleNetV2, SqueezeNet, alexnet,
    densenet121, googlenet, shufflenet_v2_x1_0, squeezenet1_1,
)
