"""paddle.vision.models parity — re-exported from paddle_tpu.models."""
