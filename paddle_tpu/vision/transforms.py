"""paddle.vision.transforms parity (numpy/Tensor-based, no PIL dependency).

Reference parity: `python/paddle/vision/transforms/`.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(x)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype("float32") / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr.astype("float32")


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype="float32")
        self.std = np.asarray(std, dtype="float32")
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype="float32")
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (arr - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img, dtype="float32")
        import jax
        import jax.numpy as jnp
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        elif arr.ndim == 3:
            out_shape = self.size + (arr.shape[2],)
        else:
            out_shape = self.size
        return np.asarray(jax.image.resize(jnp.asarray(arr), out_shape, "linear"))


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            ax = -2
            return np.flip(arr, axis=ax).copy()
        return arr


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (self.padding, self.padding)
            pads[w_ax] = (self.padding, self.padding)
            arr = np.pad(arr, pads)
        th, tw = self.size
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        i = (arr.shape[h_ax] - th) // 2
        j = (arr.shape[w_ax] - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
