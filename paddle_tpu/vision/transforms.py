"""paddle.vision.transforms parity (numpy/Tensor-based, no PIL dependency).

Reference parity: `python/paddle/vision/transforms/`.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(x)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype("float32") / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr.astype("float32")


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype="float32")
        self.std = np.asarray(std, dtype="float32")
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype="float32")
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (arr - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img, dtype="float32")
        import jax
        import jax.numpy as jnp
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        elif arr.ndim == 3:
            out_shape = self.size + (arr.shape[2],)
        else:
            out_shape = self.size
        return np.asarray(jax.image.resize(jnp.asarray(arr), out_shape, "linear"))


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            ax = -2
            return np.flip(arr, axis=ax).copy()
        return arr


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (self.padding, self.padding)
            pads[w_ax] = (self.padding, self.padding)
            arr = np.pad(arr, pads)
        th, tw = self.size
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        i = (arr.shape[h_ax] - th) // 2
        j = (arr.shape[w_ax] - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


# ---- functional image ops (transforms/functional.py parity) ----
# All HWC numpy-based (PIL-free, like the rest of this module); scipy
# supplies the rotation resample.

def hflip(img):
    return np.ascontiguousarray(np.asarray(img)[:, ::-1])


def vflip(img):
    return np.ascontiguousarray(np.asarray(img)[::-1])


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = np.asarray(img)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    h, w = arr.shape[0], arr.shape[1]
    top = max((h - oh) // 2, 0)
    left = max((w - ow) // 2, 0)
    return crop(arr, top, left, oh, ow)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    if isinstance(padding, int):
        l = r = t = b = padding
    elif len(padding) == 2:
        l, t = padding
        r, b = padding
    else:
        l, t, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, pads, mode=mode, **kw)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    from scipy import ndimage
    arr = np.asarray(img)
    order = {"nearest": 0, "bilinear": 1, "bicubic": 3}.get(interpolation, 0)
    out = ndimage.rotate(arr, -angle, axes=(1, 0), reshape=expand,
                         order=order, mode="constant", cval=fill)
    return out.astype(arr.dtype)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img).astype("float32")
    if arr.ndim == 2:
        g = arr
    else:
        g = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    g = g[..., None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return g.astype(np.asarray(img).dtype)


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img)
    out = arr.astype("float32") * brightness_factor
    if arr.dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(arr.dtype)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img)
    f = arr.astype("float32")
    gray_mean = (f[..., 0] * 0.299 + f[..., 1] * 0.587
                 + f[..., 2] * 0.114).mean() if f.ndim == 3 else f.mean()
    out = gray_mean + contrast_factor * (f - gray_mean)
    if arr.dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(arr.dtype)


def adjust_saturation(img, saturation_factor):
    arr = np.asarray(img)
    f = arr.astype("float32")
    g = to_grayscale(arr, 3).astype("float32")
    out = g + saturation_factor * (f - g)
    if arr.dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(arr.dtype)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via HSV round trip."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} not in [-0.5, 0.5]")
    arr = np.asarray(img)
    f = arr.astype("float32") / (255.0 if arr.dtype == np.uint8 else 1.0)
    mx = f.max(-1)
    mn = f.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    h = np.where(mx == r, ((g - b) / diff) % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6
    h = (h + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    i = np.floor(h * 6).astype(int) % 6
    frac = h * 6 - np.floor(h * 6)
    p = v * (1 - s)
    q = v * (1 - frac * s)
    t = v * (1 - (1 - frac) * s)
    out = np.zeros_like(f)
    for idx, (rr, gg, bb) in enumerate([(v, t, p), (q, v, p), (p, v, t),
                                        (p, q, v), (t, p, v), (v, p, q)]):
        m = i == idx
        out[..., 0][m] = rr[m]
        out[..., 1][m] = gg[m]
        out[..., 2][m] = bb[m]
    if arr.dtype == np.uint8:
        return np.clip(out * 255.0, 0, 255).astype(np.uint8)
    return out.astype(arr.dtype)


# ---- random/color transform classes ----

class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[0], arr.shape[1]
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                patch = crop(arr, top, left, ch, cw)
                return resize(patch, self.size, self.interpolation)
        return resize(center_crop(arr, min(h, w)), self.size,
                      self.interpolation)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, (int, float)):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation, self.expand = interpolation, expand
        self.center, self.fill = center, fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.n)
