"""paddle.vision.datasets parity.

Reference parity: `python/paddle/vision/datasets/` (MNIST, Cifar10/100,
FashionMNIST, Flowers). This image is zero-egress, so every dataset reads a
local file when present (same formats the reference downloads) and otherwise
generates a deterministic synthetic stand-in with identical shapes/dtypes —
keeping model code and tests identical to the reference's usage.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..io.dataset import Dataset


def _synthetic_images(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    xs = (rng.rand(n, *shape) * 255).astype("uint8")
    ys = rng.randint(0, num_classes, (n,)).astype("int64")
    # make classes separable: add a class-dependent bright band
    for i in range(n):
        c = int(ys[i])
        row = (c * shape[-2]) // num_classes
        xs[i, ..., row:row + 2, :] = 255
    return xs, ys


class MNIST(Dataset):
    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        n = 2048 if mode == "train" else 512
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(num, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), dtype=np.uint8).astype("int64")
        else:
            self.images, self.labels = _synthetic_images(n, (28, 28), 10,
                                                         0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype("float32")[None] / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend="cv2"):
        self.transform = transform
        n = 2048 if mode == "train" else 512
        if data_file and os.path.exists(data_file):
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self.images = d[b"data"].reshape(-1, 3, 32, 32)
            self.labels = np.asarray(d[b"labels"], dtype="int64")
        else:
            self.images, self.labels = _synthetic_images(
                n, (3, 32, 32), self.NUM_CLASSES, 2 if mode == "train" else 3)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype("float32") / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.transform = transform
        n = 512 if mode == "train" else 128
        self.images, self.labels = _synthetic_images(n, (3, 64, 64), 102, 4)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype("float32") / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class DatasetFolder(Dataset):
    """Directory-per-class dataset (`vision/datasets/folder.py`): walks
    root/<class_x>/*.<ext>, maps class dirs to indices."""

    IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".webp",
                      ".npy")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(e.lower() for e in (extensions or self.IMG_EXTENSIONS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    p = os.path.join(dirpath, f)
                    ok = is_valid_file(p) if is_valid_file is not None \
                        else p.lower().endswith(exts)
                    if ok:
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root!r}")

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        from PIL import Image
        return np.asarray(Image.open(path).convert("RGB"))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/unlabelled image folder (`vision/datasets/folder.py`
    ImageFolder): every valid file under root, no targets."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        exts = tuple(e.lower() for e in (extensions
                                         or DatasetFolder.IMG_EXTENSIONS))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                p = os.path.join(dirpath, f)
                ok = is_valid_file(p) if is_valid_file is not None \
                    else p.lower().endswith(exts)
                if ok:
                    self.samples.append(p)
        if not self.samples:
            raise RuntimeError(f"no valid files under {root!r}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """VOC2012 segmentation pairs (`vision/datasets/voc2012.py`). Like the
    other vision datasets in this build, a local copy is required
    (`data_file=`) — there is no network egress; SYNTHETIC mode generates
    deterministic image/mask pairs for tests."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, n_synthetic=32, seed=0):
        self.transform = transform
        self.mode = mode
        self._files = None
        if data_file is not None:
            # extracted VOCdevkit tree: JPEGImages/*.jpg paired with
            # SegmentationClass/*.png by the ImageSets/Segmentation split
            root = data_file
            for sub in ("VOCdevkit/VOC2012", "VOC2012", ""):
                cand = os.path.join(root, sub) if sub else root
                if os.path.isdir(os.path.join(cand, "JPEGImages")):
                    root = cand
                    break
            split = {"train": "train", "valid": "val", "test": "val",
                     "val": "val"}[mode]
            lst = os.path.join(root, "ImageSets", "Segmentation",
                               split + ".txt")
            with open(lst) as fh:
                ids = [ln.strip() for ln in fh if ln.strip()]
            self._files = [
                (os.path.join(root, "JPEGImages", i + ".jpg"),
                 os.path.join(root, "SegmentationClass", i + ".png"))
                for i in ids]
            return
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self._imgs = (rng.rand(n_synthetic, 3, 64, 64) * 255).astype("uint8")
        self._masks = rng.randint(0, 21, (n_synthetic, 64, 64)).astype("int64")

    def __getitem__(self, idx):
        if self._files is not None:
            from PIL import Image
            jp, mp = self._files[idx]
            img = np.asarray(Image.open(jp).convert("RGB"))
            mask = np.asarray(Image.open(mp)).astype("int64")
        else:
            img, mask = self._imgs[idx], self._masks[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self._files) if self._files is not None else len(self._imgs)
