"""paddle.vision.datasets parity.

Reference parity: `python/paddle/vision/datasets/` (MNIST, Cifar10/100,
FashionMNIST, Flowers). This image is zero-egress, so every dataset reads a
local file when present (same formats the reference downloads) and otherwise
generates a deterministic synthetic stand-in with identical shapes/dtypes —
keeping model code and tests identical to the reference's usage.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..io.dataset import Dataset


def _synthetic_images(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    xs = (rng.rand(n, *shape) * 255).astype("uint8")
    ys = rng.randint(0, num_classes, (n,)).astype("int64")
    # make classes separable: add a class-dependent bright band
    for i in range(n):
        c = int(ys[i])
        row = (c * shape[-2]) // num_classes
        xs[i, ..., row:row + 2, :] = 255
    return xs, ys


class MNIST(Dataset):
    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        n = 2048 if mode == "train" else 512
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(num, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), dtype=np.uint8).astype("int64")
        else:
            self.images, self.labels = _synthetic_images(n, (28, 28), 10,
                                                         0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype("float32")[None] / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend="cv2"):
        self.transform = transform
        n = 2048 if mode == "train" else 512
        if data_file and os.path.exists(data_file):
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self.images = d[b"data"].reshape(-1, 3, 32, 32)
            self.labels = np.asarray(d[b"labels"], dtype="int64")
        else:
            self.images, self.labels = _synthetic_images(
                n, (3, 32, 32), self.NUM_CLASSES, 2 if mode == "train" else 3)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype("float32") / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.transform = transform
        n = 512 if mode == "train" else 128
        self.images, self.labels = _synthetic_images(n, (3, 64, 64), 102, 4)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype("float32") / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)
