"""PP-YOLOE-style detector (config 4: conv-heavy inference).

Reference parity: PP-YOLOE as served through Paddle Inference in the
reference ecosystem (CSPRepResNet backbone + PAN neck + ET-head, simplified
to the inference-relevant compute graph: RepVGG-style blocks fold to single
convs at deploy time, which is what the XLA program sees anyway).

`data_format="NHWC"` puts channels on the TPU lane dimension (same deploy
layout rationale as models/resnet.py).
"""
from __future__ import annotations

from .. import nn
from ..ops.manipulation import concat


class ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k=3, stride=1, groups=1, act="silu",
                 data_format="NCHW"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=(k - 1) // 2,
                              groups=groups, bias_attr=False,
                              data_format=data_format)
        self.bn = nn.BatchNorm2D(out_c, data_format=data_format)
        self.act = nn.Silu() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class CSPResStage(nn.Layer):
    def __init__(self, in_c, out_c, n_blocks, stride=2, data_format="NCHW"):
        super().__init__()
        df = data_format
        self.down = ConvBNAct(in_c, out_c, 3, stride=stride, data_format=df)
        mid = out_c // 2
        self.conv1 = ConvBNAct(out_c, mid, 1, data_format=df)
        self.conv2 = ConvBNAct(out_c, mid, 1, data_format=df)
        self.blocks = nn.Sequential(*[
            nn.Sequential(ConvBNAct(mid, mid, 3, data_format=df),
                          ConvBNAct(mid, mid, 3, data_format=df))
            for _ in range(n_blocks)])
        self.fuse = ConvBNAct(out_c, out_c, 1, data_format=df)
        self._cat_axis = -1 if df == "NHWC" else 1

    def forward(self, x):
        x = self.down(x)
        a = self.conv1(x)
        b = self.blocks(self.conv2(x))
        return self.fuse(concat([a, b], axis=self._cat_axis))


class PPYOLOEBackbone(nn.Layer):
    def __init__(self, width_mult=0.5, depth_mult=0.33, data_format="NCHW"):
        super().__init__()
        df = data_format
        w = lambda c: max(8, int(c * width_mult))
        d = lambda n: max(1, round(n * depth_mult))
        self.stem = nn.Sequential(ConvBNAct(3, w(32), 3, stride=2, data_format=df),
                                  ConvBNAct(w(32), w(64), 3, stride=2,
                                            data_format=df))
        self.stage1 = CSPResStage(w(64), w(128), d(3), data_format=df)
        self.stage2 = CSPResStage(w(128), w(256), d(6), data_format=df)
        self.stage3 = CSPResStage(w(256), w(512), d(3), data_format=df)
        self.out_channels = [w(128), w(256), w(512)]

    def forward(self, x):
        x = self.stem(x)
        c3 = self.stage1(x)
        c4 = self.stage2(c3)
        c5 = self.stage3(c4)
        return c3, c4, c5


class PPYOLOEHead(nn.Layer):
    def __init__(self, in_channels, num_classes=80, num_anchors=1,
                 data_format="NCHW"):
        super().__init__()
        self.heads = nn.LayerList([
            nn.Conv2D(c, num_anchors * (5 + num_classes), 1,
                      data_format=data_format) for c in in_channels])

    def forward(self, feats):
        return [h(f) for h, f in zip(self.heads, feats)]


class PPYOLOE(nn.Layer):
    def __init__(self, num_classes=80, width_mult=0.5, depth_mult=0.33,
                 data_format="NCHW"):
        super().__init__()
        self.backbone = PPYOLOEBackbone(width_mult, depth_mult,
                                        data_format=data_format)
        self.head = PPYOLOEHead(self.backbone.out_channels, num_classes,
                                data_format=data_format)

    def forward(self, x):
        return self.head(self.backbone(x))


def ppyoloe_s(**kw):
    return PPYOLOE(width_mult=0.5, depth_mult=0.33, **kw)


def ppyoloe_m(**kw):
    return PPYOLOE(width_mult=0.75, depth_mult=0.67, **kw)


def ppyoloe_l(**kw):
    return PPYOLOE(width_mult=1.0, depth_mult=1.0, **kw)
