"""AlexNet / SqueezeNet / ShuffleNetV2 / DenseNet / GoogLeNet.

Reference parity: `python/paddle/vision/models/{alexnet,squeezenet,
shufflenetv2,densenet,googlenet}.py` — class/ctor surface and parameter
geometry; bodies are fresh jnp/Layer compositions (NCHW, paddle-convention
Linear [in, out]).
"""
from __future__ import annotations

from .. import nn


class AlexNet(nn.Layer):
    """vision/models/alexnet.py parity (~61.1M params at 1000 classes)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
            nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = x.reshape([x.shape[0], -1])
        return self.classifier(x)


def alexnet(**kw):
    return AlexNet(**kw)


class _Fire(nn.Layer):
    def __init__(self, cin, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(cin, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.act = nn.ReLU()

    def forward(self, x):
        import paddle_tpu as paddle
        s = self.act(self.squeeze(x))
        return paddle.concat([self.act(self.expand1(s)),
                              self.act(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    """vision/models/squeezenet.py parity (v1.1 ~1.24M / v1.0 ~1.25M)."""

    def __init__(self, num_classes=1000, version="1.1"):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2),
                _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.head = nn.Sequential(nn.Dropout(0.5),
                                  nn.Conv2D(512, num_classes, 1), nn.ReLU(),
                                  nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.head(self.features(x))
        return x.reshape([x.shape[0], -1])


def squeezenet1_1(**kw):
    return SqueezeNet(**kw)


class _ShuffleUnit(nn.Layer):
    def __init__(self, cin, cout, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = cout // 2
        mk_act = (lambda: nn.Swish()) if act == "swish" else (lambda: nn.ReLU())
        if stride == 2:
            self.b1 = nn.Sequential(
                nn.Conv2D(cin, cin, 3, stride=2, padding=1, groups=cin),
                nn.BatchNorm2D(cin),
                nn.Conv2D(cin, branch, 1), nn.BatchNorm2D(branch), mk_act())
            c2in = cin
        else:
            self.b1 = None
            c2in = cin // 2
        self.b2 = nn.Sequential(
            nn.Conv2D(c2in, branch, 1), nn.BatchNorm2D(branch), mk_act(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1), nn.BatchNorm2D(branch), mk_act())

    def forward(self, x):
        import paddle_tpu as paddle
        if self.stride == 2:
            out = paddle.concat([self.b1(x), self.b2(x)], axis=1)
        else:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = paddle.concat([x1, self.b2(x2)], axis=1)
        # channel shuffle (2 groups)
        n, c, h, w = out.shape
        out = out.reshape([n, 2, c // 2, h, w]).transpose([0, 2, 1, 3, 4])
        return out.reshape([n, c, h, w])


class ShuffleNetV2(nn.Layer):
    """vision/models/shufflenetv2.py parity (x1.0, ~2.28M params)."""

    def __init__(self, num_classes=1000, scale=1.0, act="relu"):
        super().__init__()
        self._act = act
        stages = {0.25: [24, 48, 96, 512], 0.33: [32, 64, 128, 512],
                  0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                  1.5: [176, 352, 704, 1024], 2.0: [244, 488, 976, 2048]}[scale]
        self.stem = nn.Sequential(nn.Conv2D(3, 24, 3, stride=2, padding=1),
                                  nn.BatchNorm2D(24), nn.ReLU(),
                                  nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        cin = 24
        for cout, reps in zip(stages[:3], (4, 8, 4)):
            blocks.append(_ShuffleUnit(cin, cout, 2, act=act))
            for _ in range(reps - 1):
                blocks.append(_ShuffleUnit(cout, cout, 1, act=act))
            cin = cout
        self.stages = nn.Sequential(*blocks)
        self.tail = nn.Sequential(nn.Conv2D(cin, stages[3], 1),
                                  nn.BatchNorm2D(stages[3]), nn.ReLU(),
                                  nn.AdaptiveAvgPool2D(1))
        self.fc = nn.Linear(stages[3], num_classes)

    def forward(self, x):
        x = self.tail(self.stages(self.stem(x)))
        return self.fc(x.reshape([x.shape[0], -1]))


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x0_25(**kw):
    return ShuffleNetV2(scale=0.25, **kw)


def shufflenet_v2_x0_33(**kw):
    return ShuffleNetV2(scale=0.33, **kw)


def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(scale=2.0, **kw)


def shufflenet_v2_swish(**kw):
    return ShuffleNetV2(scale=1.0, act="swish", **kw)


class _DenseLayer(nn.Layer):
    def __init__(self, cin, growth, bn_size=4):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(cin), nn.ReLU(),
            nn.Conv2D(cin, bn_size * growth, 1),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1))

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([x, self.fn(x)], axis=1)


class DenseNet(nn.Layer):
    """vision/models/densenet.py parity (121: ~7.98M params)."""

    def __init__(self, layers=(6, 12, 24, 16), growth=32, num_classes=1000,
                 init_features=64):
        super().__init__()
        c = init_features
        feats = [nn.Conv2D(3, c, 7, stride=2, padding=3),
                 nn.BatchNorm2D(c), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        for bi, n in enumerate(layers):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth))
                c += growth
            if bi != len(layers) - 1:
                feats += [nn.BatchNorm2D(c), nn.ReLU(),
                          nn.Conv2D(c, c // 2, 1), nn.AvgPool2D(2, stride=2)]
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU(), nn.AdaptiveAvgPool2D(1)]
        self.features = nn.Sequential(*feats)
        self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        return self.fc(x.reshape([x.shape[0], -1]))


def densenet121(**kw):
    return DenseNet(layers=(6, 12, 24, 16), **kw)


def densenet161(**kw):
    return DenseNet(layers=(6, 12, 36, 24), growth=48, init_features=96, **kw)


def densenet169(**kw):
    return DenseNet(layers=(6, 12, 32, 32), **kw)


def densenet201(**kw):
    return DenseNet(layers=(6, 12, 48, 32), **kw)


def densenet264(**kw):
    return DenseNet(layers=(6, 12, 64, 48), **kw)


class _Inception(nn.Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(cin, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(cin, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(cin, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(cin, pp, 1), nn.ReLU())

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b1(x), self.b2(x), self.b3(x),
                              self.b4(x)], axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = nn.Conv2D(cin, 128, 1)
        self.act = nn.ReLU()
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.drop = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.act(self.conv(self.pool(x)))
        x = self.fc1(x.reshape([x.shape[0], -1]))
        return self.fc2(self.drop(self.act(x)))


class GoogLeNet(nn.Layer):
    """vision/models/googlenet.py parity (inception v1 + two aux heads;
    forward returns [out, aux1, aux2] like the reference)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.gap = nn.AdaptiveAvgPool2D(1)
        self.drop = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)
        self.aux1 = _AuxHead(512, num_classes)
        self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.i3b(self.i3a(self.stem(x)))
        x = self.i4a(self.pool3(x))
        a1 = self.aux1(x)
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = self.aux2(x)
        x = self.i5b(self.i5a(self.pool4(self.i4e(x))))
        out = self.fc(self.drop(self.gap(x)).reshape([x.shape[0], -1]))
        return [out, a1, a2]


def googlenet(**kw):
    return GoogLeNet(**kw)


def squeezenet1_0(**kw):
    return SqueezeNet(version="1.0", **kw)


class _IncA(nn.Layer):
    """InceptionV3 figure-5 block (35x35)."""

    def __init__(self, cin, pool_ch):
        super().__init__()
        self.b1 = _ConvBN(cin, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(cin, 48, 1), _ConvBN(48, 64, 5, p=2))
        self.b3 = nn.Sequential(_ConvBN(cin, 64, 1), _ConvBN(64, 96, 3, p=1),
                                _ConvBN(96, 96, 3, p=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(cin, pool_ch, 1))

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b1(x), self.b5(x), self.b3(x),
                              self.bp(x)], axis=1)


class _ConvBN(nn.Layer):
    def __init__(self, cin, cout, k, s=1, p=0):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=s, padding=p,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.ReLU()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _IncRedA(nn.Layer):
    """figure-10 grid reduction 35->17."""

    def __init__(self, cin):
        super().__init__()
        self.b3 = _ConvBN(cin, 384, 3, s=2)
        self.b33 = nn.Sequential(_ConvBN(cin, 64, 1), _ConvBN(64, 96, 3, p=1),
                                 _ConvBN(96, 96, 3, s=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b3(x), self.b33(x), self.pool(x)], axis=1)


class _IncB(nn.Layer):
    """figure-6 block (17x17, factorized 7x7)."""

    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = _ConvBN(cin, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(cin, c7, 1), _ConvBN(c7, c7, (1, 7), p=(0, 3)),
            _ConvBN(c7, 192, (7, 1), p=(3, 0)))
        self.b77 = nn.Sequential(
            _ConvBN(cin, c7, 1), _ConvBN(c7, c7, (7, 1), p=(3, 0)),
            _ConvBN(c7, c7, (1, 7), p=(0, 3)),
            _ConvBN(c7, c7, (7, 1), p=(3, 0)),
            _ConvBN(c7, 192, (1, 7), p=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(cin, 192, 1))

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b1(x), self.b7(x), self.b77(x),
                              self.bp(x)], axis=1)


class _IncRedB(nn.Layer):
    """grid reduction 17->8."""

    def __init__(self, cin):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(cin, 192, 1), _ConvBN(192, 320, 3, s=2))
        self.b7 = nn.Sequential(
            _ConvBN(cin, 192, 1), _ConvBN(192, 192, (1, 7), p=(0, 3)),
            _ConvBN(192, 192, (7, 1), p=(3, 0)), _ConvBN(192, 192, 3, s=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncC(nn.Layer):
    """figure-7 block (8x8, expanded filter bank)."""

    def __init__(self, cin):
        super().__init__()
        self.b1 = _ConvBN(cin, 320, 1)
        self.b3_stem = _ConvBN(cin, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), p=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), p=(1, 0))
        self.b33_stem = nn.Sequential(_ConvBN(cin, 448, 1),
                                      _ConvBN(448, 384, 3, p=1))
        self.b33_a = _ConvBN(384, 384, (1, 3), p=(0, 1))
        self.b33_b = _ConvBN(384, 384, (3, 1), p=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(cin, 192, 1))

    def forward(self, x):
        import paddle_tpu as paddle
        s3 = self.b3_stem(x)
        s33 = self.b33_stem(x)
        return paddle.concat(
            [self.b1(x), self.b3_a(s3), self.b3_b(s3),
             self.b33_a(s33), self.b33_b(s33), self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """vision/models/inceptionv3.py parity (~23.8M params, 299x299 input)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, s=2), _ConvBN(32, 32, 3), _ConvBN(32, 64, 3, p=1),
            nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncRedA(288),
            _IncB(768, 128), _IncB(768, 160), _IncB(768, 160), _IncB(768, 192),
            _IncRedB(768),
            _IncC(1280), _IncC(2048))
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.pool(self.blocks(self.stem(x)))
        return self.fc(x.reshape([x.shape[0], -1]))


def inception_v3(**kw):
    return InceptionV3(**kw)
