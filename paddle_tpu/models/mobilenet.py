"""MobileNetV1/V2. Reference parity: `python/paddle/vision/models/mobilenet*.py`."""
from __future__ import annotations

from .. import nn
from ..ops.manipulation import flatten


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1, act=True):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = nn.ReLU6() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = ConvBNLayer(in_c, in_c, 3, stride=stride, padding=1, groups=in_c)
        self.pw = ConvBNLayer(in_c, out_c, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: max(8, int(c * scale))
        cfg = [(s(32), s(64), 1), (s(64), s(128), 2), (s(128), s(128), 1),
               (s(128), s(256), 2), (s(256), s(256), 1), (s(256), s(512), 2)] + \
              [(s(512), s(512), 1)] * 5 + [(s(512), s(1024), 2), (s(1024), s(1024), 1)]
        self.stem = ConvBNLayer(3, s(32), 3, stride=2, padding=1)
        self.blocks = nn.Sequential(*[DepthwiseSeparable(i, o, st) for i, o, st in cfg])
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand):
        super().__init__()
        hidden = int(round(in_c * expand))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand != 1:
            layers.append(ConvBNLayer(in_c, hidden, 1))
        layers += [ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                               groups=hidden),
                   ConvBNLayer(hidden, out_c, 1, act=False)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = max(8, int(32 * scale))
        self.stem = ConvBNLayer(3, in_c, 3, stride=2, padding=1)
        blocks = []
        for t, c, n, s in cfg:
            out_c = max(8, int(c * scale))
            for i in range(n):
                blocks.append(InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        last = max(8, int(1280 * scale)) if scale > 1.0 else 1280
        blocks.append(ConvBNLayer(in_c, last, 1))
        self.blocks = nn.Sequential(*blocks)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(last, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
