"""PP-OCRv3-style text recognizer: conv backbone -> BiLSTM -> CTC head.

Reference parity: the PP-OCRv3 recognition pipeline served through Paddle
Inference in the reference ecosystem (MobileNet-style backbone + sequence
encoder + CTC head — the SVTR/CRNN "rec" half of BASELINE config 4; the
BiLSTM encoder is `paddle.nn.LSTM(direction='bidirect')`, rnn.py:1212).

TPU-first notes: NHWC keeps channels on the lane dimension through the conv
stack; the height axis is pooled away before the sequence stage so the
BiLSTM sees one [B, W', C] sequence whose whole sweep compiles to a single
pair of lax.scans (see nn/layer/rnn.py); the CTC loss is the scanned
log-semiring DP in nn/functional/loss.py — no warpctc kernel.
"""
from __future__ import annotations

from .. import nn
from ..nn import functional as F


class _ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k=3, stride=1, groups=1,
                 data_format="NHWC"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False, data_format=data_format)
        self.bn = nn.BatchNorm2D(out_c, data_format=data_format)
        self.act = nn.Hardswish()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride, data_format="NHWC"):
        super().__init__()
        self.dw = _ConvBNAct(in_c, in_c, 3, stride=stride, groups=in_c,
                             data_format=data_format)
        self.pw = _ConvBNAct(in_c, out_c, 1, data_format=data_format)

    def forward(self, x):
        return self.pw(self.dw(x))


class RecBackbone(nn.Layer):
    """MobileNetV1-style rec backbone; strides shrink H aggressively and W
    gently so the output keeps a long width axis for the sequence stage.
    """

    def __init__(self, in_channels=3, scale=0.5, data_format="NHWC"):
        super().__init__()
        c = lambda ch: max(8, int(ch * scale))
        df = data_format
        # (out_c, stride): stride (2,1) halves H only — keeps sequence length
        cfg = [(64, (2, 1)), (128, (1, 1)), (128, (2, 1)), (256, (1, 1)),
               (256, (2, 1)), (512, (1, 1))]
        self.stem = _ConvBNAct(in_channels, c(32), 3, stride=2, data_format=df)
        blocks = []
        in_c = c(32)
        for out_c, stride in cfg:
            blocks.append(_DepthwiseSeparable(in_c, c(out_c), stride, df))
            in_c = c(out_c)
        self.blocks = nn.Sequential(*blocks)
        self.out_channels = in_c
        self.data_format = df

    def forward(self, x):
        return self.blocks(self.stem(x))


class SequenceEncoder(nn.Layer):
    """Pool H away, then a bidirectional LSTM over the width axis."""

    def __init__(self, in_channels, hidden_size=48, num_layers=2):
        super().__init__()
        self.lstm = nn.LSTM(in_channels, hidden_size, num_layers=num_layers,
                            direction="bidirect")
        self.out_channels = hidden_size * 2

    def forward(self, x):
        # x: [B, H', W', C] (NHWC) -> [B, W', C]
        x = x.mean(axis=1)
        out, _ = self.lstm(x)
        return out


class CTCHead(nn.Layer):
    def __init__(self, in_channels, n_classes):
        super().__init__()
        self.fc = nn.Linear(in_channels, n_classes)

    def forward(self, x):
        return self.fc(x)


class PPOCRRec(nn.Layer):
    """End-to-end recognizer. Input [B, 32, W, 3] NHWC images; output
    per-position logits [B, W/2, n_classes] (class 0 = CTC blank — only
    the stem strides the width axis; the stage strides shrink H only)."""

    def __init__(self, n_classes=6625, scale=0.5, hidden_size=48,
                 data_format="NHWC"):
        super().__init__()
        if data_format != "NHWC":
            raise ValueError(
                "PPOCRRec is NHWC-only (TPU deploy layout); the sequence "
                f"neck pools the height axis — got {data_format}")
        self.backbone = RecBackbone(3, scale, data_format)
        self.neck = SequenceEncoder(self.backbone.out_channels, hidden_size)
        self.head = CTCHead(self.neck.out_channels, n_classes)
        self.n_classes = n_classes

    def forward(self, x):
        return self.head(self.neck(self.backbone(x)))

    def loss(self, logits, labels, label_lengths):
        """CTC training loss; every input width position is a valid step."""
        import numpy as np
        T = logits.shape[1]
        B = logits.shape[0]
        logits_tm = logits.transpose([1, 0, 2])   # -> [T, B, C]
        input_lengths = np.full((B,), T, "int64")
        return F.ctc_loss(logits_tm, labels, input_lengths, label_lengths,
                          blank=0, reduction="mean")


def pp_ocrv3_rec(n_classes=6625, **kw):
    return PPOCRRec(n_classes=n_classes, **kw)
