"""ERNIE/BERT encoder family — the flagship benchmark model (config 3).

Reference parity: ERNIE as consumed through PaddleNLP on the reference stack
(transformer encoder per `python/paddle/nn/layer/transformer.py`, trained
via Fleet). The TPU build wires tensor-parallel variants through
paddle_tpu.parallel.mp_layers so the same class scales from one chip to a
pod slice; attention lowers to the fused XLA/Pallas path.

Configs: ernie_base (12L/768H/12A — BERT-base geometry), ernie_large,
ernie_titan_10b approximation (48L/4096H/64A ≈ 10B params) for config 5.
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..ops.creation import arange, ones, zeros
from ..ops.manipulation import reshape, unsqueeze


class ErnieEmbeddings(nn.Layer):
    def __init__(self, vocab_size, hidden_size, max_position_embeddings=512,
                 type_vocab_size=2, dropout=0.1, use_mp=False):
        super().__init__()
        if use_mp:
            from ..parallel.mp_layers import VocabParallelEmbedding
            self.word_embeddings = VocabParallelEmbedding(vocab_size, hidden_size)
        else:
            self.word_embeddings = nn.Embedding(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(max_position_embeddings, hidden_size)
        self.token_type_embeddings = nn.Embedding(type_vocab_size, hidden_size)
        self.layer_norm = nn.LayerNorm(hidden_size)
        self.dropout = nn.Dropout(dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = arange(seq_len, dtype="int32")
            position_ids = unsqueeze(position_ids, 0)
        if token_type_ids is None:
            token_type_ids = zeros(list(input_ids.shape), dtype="int32")
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class ErnieMLP(nn.Layer):
    def __init__(self, hidden_size, intermediate_size, dropout=0.1, use_mp=False):
        super().__init__()
        if use_mp:
            from ..parallel.mp_layers import ColumnParallelLinear, RowParallelLinear
            self.fc1 = ColumnParallelLinear(hidden_size, intermediate_size,
                                            gather_output=False)
            self.fc2 = RowParallelLinear(intermediate_size, hidden_size,
                                         input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(hidden_size, intermediate_size)
            self.fc2 = nn.Linear(intermediate_size, hidden_size)
        self.act = nn.GELU()
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        return self.dropout(self.fc2(self.act(self.fc1(x))))


class ErnieSelfAttention(nn.Layer):
    def __init__(self, hidden_size, num_heads, dropout=0.1, use_mp=False,
                 use_sp=False, causal=False):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        self.causal = causal
        self.use_sp = use_sp
        if use_mp:
            from ..parallel.mp_layers import ColumnParallelLinear, RowParallelLinear
            self.qkv = ColumnParallelLinear(hidden_size, 3 * hidden_size,
                                            gather_output=True)
            self.out = RowParallelLinear(hidden_size, hidden_size)
        else:
            self.qkv = nn.Linear(hidden_size, 3 * hidden_size)
            self.out = nn.Linear(hidden_size, hidden_size)
        self.dropout_p = dropout

    def forward(self, x, attn_mask=None):
        from ..nn.functional.attention import scaled_dot_product_attention
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv(x)
        qkv = reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.use_sp:
            from ..parallel.sp import sequence_parallel_attention
            ctx = sequence_parallel_attention(q, k, v, impl="ring", causal=self.causal)
        else:
            ctx = scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=self.dropout_p if self.training else 0.0,
                is_causal=self.causal, training=self.training)
        ctx = reshape(ctx, [b, s, self.num_heads * self.head_dim])
        return self.out(ctx)

    def forward_cached(self, x, k_cache, v_cache, positions,
                       k_scale=None, v_scale=None):
        """Cached-attention step over a fixed-shape KV cache (decode path).

        x: [B, T, H] current block (T = prompt length at prefill, 1 at
        decode). k_cache/v_cache: [B, L, nh, hd] with L fixed (the slot
        page) — fp32, or int8 for the weight-only KV arm. positions: [B]
        int32, tokens already cached per row; the block's K/V are written
        at positions[b]..positions[b]+T-1 and attention runs over the
        whole page under a validity mask (key j visible to query i iff
        j <= positions[b]+i), so every (B, T, L) signature is ONE
        executable regardless of how full each row is.

        int8 mode (k_cache.dtype == int8): scale-per-row symmetric
        quantization. With k_scale/v_scale None the scales are computed
        fresh from this block's K/V (the prefill step); otherwise the
        given [B] scales are reused and new entries clip into their grid
        (the decode steps). Reads always dequantize cache * scale.

        Inference-only: dropout is not applied inside the attention (the
        surrounding norms/MLP still honor train/eval mode). Returns
        (out, k_cache, v_cache, k_scale, v_scale) — scales are None in
        fp32 mode.
        """
        import math as _math

        import jax
        import jax.numpy as jnp

        from ..ops._dispatch import run_op
        from ..ops.math import _precision

        b, t = x.shape[0], x.shape[1]
        qkv = self.qkv(x)
        qkv = reshape(qkv, [b, t, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scale = 1.0 / _math.sqrt(self.head_dim)
        quant = "int8" in str(k_cache.dtype)
        fresh = quant and k_scale is None
        ins = [q, k, v, k_cache, v_cache, positions]
        if quant and not fresh:
            ins += [k_scale, v_scale]

        def f(qa, ka, va, kc, vc, pos, *scales):
            if quant:
                if fresh:
                    # symmetric per-row grid from this block's dynamic
                    # range; later (decode) writes clip into it
                    ks = jnp.maximum(jnp.max(jnp.abs(ka), axis=(1, 2, 3)),
                                     1e-6) / 127.0
                    vs = jnp.maximum(jnp.max(jnp.abs(va), axis=(1, 2, 3)),
                                     1e-6) / 127.0
                else:
                    ks, vs = scales
                kw = jnp.clip(jnp.round(ka / ks[:, None, None, None]),
                              -127, 127).astype(jnp.int8)
                vw = jnp.clip(jnp.round(va / vs[:, None, None, None]),
                              -127, 127).astype(jnp.int8)
            else:
                kw, vw = ka, va

            def upd(page, blk, p):
                return jax.lax.dynamic_update_slice(page, blk, (p, 0, 0))

            kc = jax.vmap(upd)(kc, kw, pos)
            vc = jax.vmap(upd)(vc, vw, pos)
            if quant:
                kr = kc.astype(qa.dtype) * ks[:, None, None, None]
                vr = vc.astype(qa.dtype) * vs[:, None, None, None]
            else:
                kr, vr = kc, vc
            # mirror scaled_dot_product_attention's fused path exactly
            # (same einsums/precision/mask value) so cached decode is
            # bit-identical to the full-sequence forward
            qh = jnp.swapaxes(qa, 1, 2)
            kh = jnp.swapaxes(kr, 1, 2)
            vh = jnp.swapaxes(vr, 1, 2)
            logits = jnp.einsum("bhsd,bhtd->bhst", qh, kh,
                                precision=_precision()) * scale
            span = jnp.arange(kh.shape[2], dtype=pos.dtype)
            qpos = pos[:, None] + jnp.arange(qa.shape[1], dtype=pos.dtype)
            valid = span[None, None, None, :] <= qpos[:, None, :, None]
            logits = jnp.where(valid, logits, jnp.asarray(-1e9, logits.dtype))
            probs = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum("bhst,bhtd->bhsd", probs, vh,
                             precision=_precision())
            out = jnp.swapaxes(out, 1, 2)
            if quant:
                return out, kc, vc, ks, vs
            return out, kc, vc

        outs = run_op(f, ins, "cached_attention")
        if quant:
            ctx, k_cache, v_cache, k_scale, v_scale = outs
        else:
            ctx, k_cache, v_cache = outs
        ctx = reshape(ctx, [b, t, self.num_heads * self.head_dim])
        return self.out(ctx), k_cache, v_cache, k_scale, v_scale


class ErnieLayer(nn.Layer):
    def __init__(self, hidden_size, num_heads, intermediate_size, dropout=0.1,
                 use_mp=False, use_sp=False, causal=False):
        super().__init__()
        self.attention = ErnieSelfAttention(hidden_size, num_heads, dropout, use_mp,
                                            use_sp, causal)
        self.mlp = ErnieMLP(hidden_size, intermediate_size, dropout, use_mp)
        self.norm1 = nn.LayerNorm(hidden_size)
        self.norm2 = nn.LayerNorm(hidden_size)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, attn_mask=None):
        x = self.norm1(x + self.dropout(self.attention(x, attn_mask)))
        x = self.norm2(x + self.mlp(x))
        return x

    def forward_cached(self, x, k_cache, v_cache, positions,
                       k_scale=None, v_scale=None):
        """One transformer block through the cached-attention path; same
        post-LN residual wiring as forward. Returns
        (x, k_cache, v_cache, k_scale, v_scale)."""
        attn, k_cache, v_cache, k_scale, v_scale = self.attention.forward_cached(
            x, k_cache, v_cache, positions, k_scale, v_scale)
        x = self.norm1(x + self.dropout(attn))
        x = self.norm2(x + self.mlp(x))
        return x, k_cache, v_cache, k_scale, v_scale


class ErnieModel(nn.Layer):
    def __init__(self, vocab_size=30522, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=3072,
                 max_position_embeddings=512, type_vocab_size=2,
                 hidden_dropout_prob=0.1, use_mp=False, use_sp=False, causal=False):
        super().__init__()
        self.embeddings = ErnieEmbeddings(vocab_size, hidden_size,
                                          max_position_embeddings, type_vocab_size,
                                          hidden_dropout_prob, use_mp)
        self.layers = nn.LayerList([
            ErnieLayer(hidden_size, num_attention_heads, intermediate_size,
                       hidden_dropout_prob, use_mp, use_sp, causal)
            for _ in range(num_hidden_layers)])
        self.pooler = nn.Linear(hidden_size, hidden_size)
        self.pooler_act = nn.Tanh()

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            # [B,S] 1/0 mask -> additive [B,1,1,S]
            m = unsqueeze(unsqueeze(attention_mask, 1), 1)
            attention_mask = (1.0 - m.astype("float32")) * -1e4
        for layer in self.layers:
            x = layer(x, attention_mask)
        pooled = self.pooler_act(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, ernie: ErnieModel, num_classes=2, dropout=0.1):
        super().__init__()
        self.ernie = ernie
        self.dropout = nn.Dropout(dropout)
        self.classifier = nn.Linear(ernie.pooler.weight.shape[1], num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


class ErnieForPretraining(nn.Layer):
    """MLM + NSP heads (the pretraining objective the benchmark measures)."""

    def __init__(self, ernie: ErnieModel, use_mp=False):
        super().__init__()
        self.ernie = ernie
        hidden = ernie.pooler.weight.shape[1]
        self.transform = nn.Linear(hidden, hidden)
        self.transform_act = nn.GELU()
        self.transform_norm = nn.LayerNorm(hidden)
        self.nsp = nn.Linear(hidden, 2)
        self._use_mp = use_mp

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, attention_mask)
        h = self.transform_norm(self.transform_act(self.transform(seq)))
        # weight-tied MLM logits against the (possibly vocab-sharded) embedding.
        # Flatten to 2D first: a batched [B,S,H]x[V,H]^T dot picks a
        # {1,2,0} output layout that costs a full-logits relayout copy
        # (250MB at vocab 30k) before the loss consumes it.
        from ..ops.math import matmul
        w = self.ernie.embeddings.word_embeddings.weight
        b, s = h.shape[0], h.shape[1]
        logits = matmul(h.reshape([-1, h.shape[-1]]), w, transpose_y=True)
        logits = logits.reshape([b, s, logits.shape[-1]])
        return logits, self.nsp(pooled)


# ---- configs ----
def ernie_base(**kw):
    return ErnieModel(vocab_size=30522, hidden_size=768, num_hidden_layers=12,
                      num_attention_heads=12, intermediate_size=3072, **kw)


def ernie_large(**kw):
    return ErnieModel(vocab_size=30522, hidden_size=1024, num_hidden_layers=24,
                      num_attention_heads=16, intermediate_size=4096, **kw)


def ernie_titan_10b(**kw):
    """≈10B-parameter geometry for the sharding+pipeline config (config 5)."""
    return ErnieModel(vocab_size=50304, hidden_size=4096, num_hidden_layers=48,
                      num_attention_heads=64, intermediate_size=16384,
                      max_position_embeddings=2048, **kw)


bert_base = ernie_base
bert_large = ernie_large


class ErnieScanStack(nn.Layer):
    """N identical transformer layers as ONE scanned, rematerialized layer.

    TPU-first design for the deep (48-layer titan) stack: instead of
    unrolling 48 python layers into the HLO (48x compile time, 48x code) the
    layer weights live STACKED ([L, ...] leading axis) and the forward is
    `lax.scan(jax.checkpoint(layer_fn))`:
      - compile time and program size are O(1) in depth;
      - `jax.checkpoint` per scan step = per-layer remat, so backward peak
        activation memory is one layer's activations + L boundary tensors
        (the enabler for ZeRO-3 titan training, reference
        `sharding_stage3.py:308` + `recompute` meta-optimizer);
      - GSPMD shards the stacked weights on their hidden axes exactly like
        the unrolled layers.
    Semantics match a loop of ErnieLayer(dropout=0) (post-LN residual
    blocks); dropout is compiled out (the large-scale configs train with
    dropout 0 anyway — reference ernie titan configs).
    """

    def __init__(self, hidden_size, num_heads, intermediate_size, n_layers,
                 remat=True, causal=False):
        """remat: False = no rematerialization; True = blanket per-layer
        remat (save only layer boundaries — minimum memory, the choice for
        HBM-bound pp-stage configs, tests/test_titan_feasibility.py);
        "dots" = selective checkpoint policy (save MXU/dot outputs +
        the flash-attention output, recompute elementwise+norm only —
        the reference recompute meta-optimizer's selective `checkpoints=`
        contract, fleet/meta_optimizers/recompute_optimizer.py, mapped to
        jax.checkpoint_policies). Blanket remat recomputes the expensive
        matmuls too and caps useful-FLOP fraction near 0.75; "dots" trades
        ~10*h bytes/token/layer of HBM to keep the MXU work single-pass.
        """
        super().__init__()
        import math as _math
        h, ffn, L = hidden_size, intermediate_size, n_layers
        self.hidden_size, self.num_heads, self.n_layers = h, num_heads, L
        self.remat, self.causal = remat, causal
        k = 1.0 / _math.sqrt(h)

        def mk(*shape):
            return self.create_parameter(
                shape, default_initializer=nn.initializer.Uniform(-k, k))

        def zeros_(*shape):
            return self.create_parameter(
                shape, default_initializer=nn.initializer.Constant(0.0))

        self.qkv_w = mk(L, h, 3 * h)
        self.qkv_b = zeros_(L, 3 * h)
        self.proj_w = mk(L, h, h)
        self.proj_b = zeros_(L, h)
        self.fc1_w = mk(L, h, ffn)
        self.fc1_b = zeros_(L, ffn)
        self.fc2_w = mk(L, ffn, h)
        self.fc2_b = zeros_(L, h)
        ones_ = nn.initializer.Constant(1.0)
        self.ln1_g = self.create_parameter((L, h), default_initializer=ones_)
        self.ln1_b = zeros_(L, h)
        self.ln2_g = self.create_parameter((L, h), default_initializer=ones_)
        self.ln2_b = zeros_(L, h)
        # GSPMD layout: shard the big matrices on their widest axis
        for p, attr in ((self.qkv_w, (None, None, "mp")),
                        (self.fc1_w, (None, None, "mp")),
                        (self.fc2_w, (None, "mp", None)),
                        (self.proj_w, (None, "mp", None))):
            p.dist_attr = attr

    def _layer_fn(self, x, wl):
        import jax
        import jax.numpy as jnp
        import math as _math
        (qkv_w, qkv_b, proj_w, proj_b, fc1_w, fc1_b, fc2_w, fc2_b,
         ln1_g, ln1_b, ln2_g, ln2_b) = wl
        B, S, H = x.shape
        nh = self.num_heads
        hd = H // nh

        def ln(v, g, b):
            # statistics in fp32 (bf16 mean/var over h=4096 loses ~3 bits),
            # result back in the residual dtype so the scan carry is stable
            v32 = v.astype(jnp.float32)
            mu = jnp.mean(v32, -1, keepdims=True)
            var = jnp.var(v32, -1, keepdims=True)
            # eps matches nn.LayerNorm's default so scan-stack and unrolled
            # ErnieLayer checkpoints are interchangeable
            n = ((v32 - mu) * jax.lax.rsqrt(var + 1e-5)).astype(v.dtype)
            return n * g + b

        qkv = x @ qkv_w + qkv_b
        q, k_, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, nh, hd)
        k_ = k_.reshape(B, S, nh, hd)
        v = v.reshape(B, S, nh, hd)
        from ..kernels.flash_attention import flash_attention_arrays
        o = flash_attention_arrays(q, k_, v, causal=self.causal)
        # named save point for the selective remat policy: the pallas
        # flash output is not a lax dot, so dots_saveable alone would
        # recompute the whole attention in the backward pass
        from jax.ad_checkpoint import checkpoint_name
        o = checkpoint_name(o, "flash_attn_out")
        o = o.reshape(B, S, H) @ proj_w + proj_b
        x = ln(x + o, ln1_g, ln1_b)
        m = jax.nn.gelu(x @ fc1_w + fc1_b, approximate=False) @ fc2_w + fc2_b
        x = ln(x + m, ln2_g, ln2_b)
        return x

    def forward(self, x):
        from ..ops._dispatch import ensure_tensor, run_op
        from ..amp.state import amp_enabled, amp_state
        import jax
        import jax.numpy as jnp
        x = ensure_tensor(x)
        ws = [self.qkv_w, self.qkv_b, self.proj_w, self.proj_b,
              self.fc1_w, self.fc1_b, self.fc2_w, self.fc2_b,
              self.ln1_g, self.ln1_b, self.ln2_g, self.ln2_b]
        remat = self.remat
        # _layer_fn is raw jnp, below the op-level autocast whitelist: an
        # fp32 carry would silently promote every dot (and every saved
        # residual) back to fp32. Capture the ambient AMP dtype at trace
        # time and pin the scan carry + weights to it.
        cdtype = jnp.dtype(amp_state().dtype) if amp_enabled() else None

        def f(xa, *warrs):
            if cdtype is not None and xa.dtype != cdtype:
                xa = xa.astype(cdtype)
            if cdtype is not None:
                warrs = tuple(
                    w.astype(cdtype)
                    if jnp.issubdtype(w.dtype, jnp.floating) else w
                    for w in warrs)
            def body(carry, wl):
                step = self._layer_fn
                if remat == "dots":
                    pol = jax.checkpoint_policies.save_from_both_policies(
                        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                        jax.checkpoint_policies.save_only_these_names(
                            "flash_attn_out"))
                    step = jax.checkpoint(step, policy=pol)
                elif remat:
                    step = jax.checkpoint(step)
                return step(carry, wl), None

            out, _ = jax.lax.scan(body, xa, tuple(warrs))
            return out

        return run_op(f, [x, *ws], "ernie_scan_stack")
