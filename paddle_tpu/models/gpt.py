"""GPT-style decoder LM with hybrid-parallel wiring (config 5 engine model).

Reference parity: the fleetx/PaddleNLP GPT consumed by the reference's
hybrid-parallel examples (`fleet/meta_parallel` tests use gpt runners).
Supports: tensor parallel (mp layers), sequence parallel (ring attention),
and a PipelineLayer factory for pipeline parallelism.
"""
from __future__ import annotations

from .. import nn
from ..ops.creation import arange
from ..ops.manipulation import reshape, unsqueeze
from .ernie import ErnieLayer


class GPTEmbeddings(nn.Layer):
    def __init__(self, vocab_size, hidden_size, max_seq_len=1024, dropout=0.1,
                 use_mp=False):
        super().__init__()
        if use_mp:
            from ..parallel.mp_layers import VocabParallelEmbedding
            self.word_embeddings = VocabParallelEmbedding(vocab_size, hidden_size)
        else:
            self.word_embeddings = nn.Embedding(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(max_seq_len, hidden_size)
        self.dropout = nn.Dropout(dropout)

    def forward(self, input_ids, position_offset=None):
        pos = unsqueeze(arange(input_ids.shape[1], dtype="int32"), 0)
        if position_offset is not None:
            # cached decode: [B] tokens-already-seen offsets the block's
            # position ids so step N embeds position N, not 0
            pos = pos + reshape(position_offset, [-1, 1])
        return self.dropout(self.word_embeddings(input_ids)
                            + self.position_embeddings(pos))


class GPTModel(nn.Layer):
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None, max_seq_len=1024,
                 dropout=0.1, use_mp=False, use_sp=False):
        super().__init__()
        intermediate_size = intermediate_size or 4 * hidden_size
        self.embeddings = GPTEmbeddings(vocab_size, hidden_size, max_seq_len,
                                        dropout, use_mp)
        self.layers = nn.LayerList([
            ErnieLayer(hidden_size, num_heads, intermediate_size, dropout,
                       use_mp, use_sp, causal=True)
            for _ in range(num_layers)])
        self.final_norm = nn.LayerNorm(hidden_size)

    def forward(self, input_ids):
        x = self.embeddings(input_ids)
        for layer in self.layers:
            x = layer(x)
        return self.final_norm(x)

    def init_kv_cache(self, batch_size, max_len, dtype="float32"):
        """Fresh zero KV pages for forward_cached: one (k, v) pair per
        layer, each [batch, max_len, num_heads, head_dim]. dtype "int8"
        builds the quantized-KV pages (scales start as None and are
        computed by the first forward_cached call)."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        attn = self.layers[0].attention
        shape = (batch_size, max_len, attn.num_heads, attn.head_dim)
        return [(Tensor(jnp.zeros(shape, dtype=dtype)),
                 Tensor(jnp.zeros(shape, dtype=dtype)))
                for _ in self.layers]

    def forward_cached(self, input_ids, past_kv, positions, kv_scales=None):
        """Prefill/decode step over explicit KV-cache carries.

        input_ids [B, T]; past_kv: list over layers of (k, v) fixed-shape
        pages [B, L, nh, hd]; positions [B] int32 tokens-already-cached
        per row (also the position-embedding offset). kv_scales: list of
        (k_scale, v_scale) [B] pairs for int8 pages, or None.
        Returns (hidden, new_past_kv, new_kv_scales)."""
        x = self.embeddings(input_ids, position_offset=positions)
        new_kv, new_scales = [], []
        for i, layer in enumerate(self.layers):
            ks, vs = (None, None) if kv_scales is None else kv_scales[i]
            k, v = past_kv[i]
            x, k, v, ks, vs = layer.forward_cached(x, k, v, positions, ks, vs)
            new_kv.append((k, v))
            new_scales.append((ks, vs))
        return self.final_norm(x), new_kv, new_scales


class GPTForCausalLM(nn.Layer):
    def __init__(self, gpt: GPTModel):
        super().__init__()
        self.gpt = gpt

    def forward(self, input_ids):
        from ..ops.math import matmul
        h = self.gpt(input_ids)
        w = self.gpt.embeddings.word_embeddings.weight
        return matmul(h, w, transpose_y=True)

    def forward_cached(self, input_ids, past_kv, positions, kv_scales=None):
        """Cached-attention LM step: (logits, new_past_kv, new_kv_scales).
        Weight-tied head over GPTModel.forward_cached — a decode step
        ([B, 1] input) is one-token work against the cache pages."""
        from ..ops.math import matmul
        h, new_kv, new_scales = self.gpt.forward_cached(
            input_ids, past_kv, positions, kv_scales)
        w = self.gpt.embeddings.word_embeddings.weight
        return matmul(h, w, transpose_y=True), new_kv, new_scales


class GPTPretrainingCriterion(nn.Layer):
    """Shifted-LM loss; vocab-parallel CE when logits are sharded."""

    def __init__(self, use_parallel_ce=False):
        super().__init__()
        if use_parallel_ce:
            from ..parallel.mp_layers import ParallelCrossEntropy
            self.ce = ParallelCrossEntropy()
            self._parallel = True
        else:
            self.ce = nn.CrossEntropyLoss()
            self._parallel = False

    def forward(self, logits, labels):
        shifted = logits[:, :-1]
        tgt = labels[:, 1:]
        if self._parallel:
            return self.ce(shifted, unsqueeze(tgt, -1)).mean()
        b, s, v = shifted.shape
        return self.ce(reshape(shifted, [b * s, v]), reshape(tgt, [b * s]))


def gpt_pipeline_layer(vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
                       num_stages=2, use_mp=False, dropout=0.1, max_seq_len=1024,
                       num_virtual_pipeline_stages=1):
    """PipelineLayer build of GPT for pp training (reference pp_layers pattern)."""
    from ..parallel.pp_layers import LayerDesc, PipelineLayer

    class _EmbedStage(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = GPTEmbeddings(vocab_size, hidden_size, max_seq_len, dropout,
                                     use_mp)

        def forward(self, ids):
            return self.emb(ids)

    class _HeadStage(nn.Layer):
        def __init__(self):
            super().__init__()
            self.norm = nn.LayerNorm(hidden_size)
            self.lm_head = nn.Linear(hidden_size, vocab_size, bias_attr=False)

        def forward(self, x):
            return self.lm_head(self.norm(x))

    descs = [LayerDesc(_EmbedStage)]
    for _ in range(num_layers):
        descs.append(LayerDesc(ErnieLayer, hidden_size, num_heads, 4 * hidden_size,
                               dropout, use_mp, False, True))
    descs.append(LayerDesc(_HeadStage))
    return PipelineLayer(descs, num_stages=num_stages,
                         loss_fn=GPTPretrainingCriterion(),
                         num_virtual_pipeline_stages=num_virtual_pipeline_stages)


# configs
def gpt2_small(**kw):
    return GPTModel(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt2_medium(**kw):
    return GPTModel(hidden_size=1024, num_layers=24, num_heads=16, **kw)


def gpt_10b(**kw):
    return GPTModel(hidden_size=4096, num_layers=48, num_heads=64,
                    max_seq_len=2048, **kw)
