"""Model zoo covering the baseline configs (BASELINE.md):
LeNet (1), ResNet-50 (2), ERNIE/BERT-base (3), PP-YOLOE (4),
ERNIE-10B / GPT hybrid-parallel (5)."""
from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    BasicBlock, BottleneckBlock, ResNet, ResNeXt, resnet18, resnet34,
    resnet50, resnet101, resnet152, wide_resnet50_2, wide_resnet101_2,
    resnext50_32x4d, resnext50_64x4d, resnext101_32x4d, resnext101_64x4d,
    resnext152_32x4d, resnext152_64x4d,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2  # noqa: F401
from .ernie import (  # noqa: F401
    ErnieForPretraining, ErnieForSequenceClassification, ErnieModel, bert_base,
    bert_large, ernie_base, ernie_large, ernie_titan_10b,
)
from .gpt import (  # noqa: F401
    GPTForCausalLM, GPTModel, GPTPretrainingCriterion, gpt2_medium, gpt2_small,
    gpt_10b, gpt_pipeline_layer,
)
from .yoloe import PPYOLOE, ppyoloe_l, ppyoloe_m, ppyoloe_s  # noqa: F401
from .small_nets import (  # noqa: F401
    AlexNet, DenseNet, GoogLeNet, InceptionV3, ShuffleNetV2, SqueezeNet,
    alexnet, densenet121, densenet161, densenet169, densenet201, densenet264,
    googlenet, inception_v3, shufflenet_v2_x0_25, shufflenet_v2_x0_33,
    shufflenet_v2_x0_5, shufflenet_v2_x1_0, shufflenet_v2_x1_5,
    shufflenet_v2_x2_0, shufflenet_v2_swish, squeezenet1_0, squeezenet1_1,
)
from .pp_ocr import PPOCRRec, pp_ocrv3_rec  # noqa: F401
