"""Model zoo (filled by the models milestone)."""
