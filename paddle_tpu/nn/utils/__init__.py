from ..clip import clip_grad_norm_  # noqa: F401


def parameters_to_vector(parameters, name=None):
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    return Tensor(jnp.concatenate([p._value.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    import numpy as np
    off = 0
    arr = vec._value if hasattr(vec, "_value") else vec
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p._value = arr[off:off + n].reshape(tuple(p.shape)).astype(p._value.dtype)
        off += n


def weight_norm(layer, name="weight", dim=0):
    raise NotImplementedError("weight_norm: planned (round 2)")


def remove_weight_norm(layer, name="weight"):
    raise NotImplementedError("weight_norm: planned (round 2)")


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    raise NotImplementedError("spectral_norm: planned (round 2)")
