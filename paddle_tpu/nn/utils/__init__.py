"""nn.utils: vectorize helpers + hook-based weight reparametrizations.

Reference parity: `python/paddle/nn/utils/weight_norm_hook.py:155`
(weight_norm/remove_weight_norm) and `spectral_norm_hook.py:131`
(spectral_norm) — forward-pre-hook reparametrizations: the layer's weight
parameter is replaced by derived parameters, and every forward recomputes
the effective weight from them so the optimizer trains the derived
parameters. The recomputation is pure jnp traced through the tape, so it
jits into TrainStep like any other op.
"""
from ..clip import clip_grad_norm_  # noqa: F401


def parameters_to_vector(parameters, name=None):
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    return Tensor(jnp.concatenate([p._value.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    import numpy as np
    off = 0
    arr = vec._value if hasattr(vec, "_value") else vec
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p._value = arr[off:off + n].reshape(tuple(p.shape)).astype(p._value.dtype)
        off += n


def _norm_except_dim(v, dim):
    """L2 norm reduced over every axis except `dim` (kept) — the
    weight_norm_hook norm_except_dim contract. dim=None -> full norm."""
    from ...ops._dispatch import run_op
    import jax.numpy as jnp

    def f(a):
        if dim is None:
            return jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2)).astype(a.dtype)
        axes = tuple(i for i in range(a.ndim) if i != dim)
        return jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2,
                                axis=axes, keepdims=True)).astype(a.dtype)

    return run_op(f, [v], "norm_except_dim")


class _WeightNorm:
    def __init__(self, name, dim):
        self.name, self.dim = name, dim

    def compute_weight(self, layer):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        norm = _norm_except_dim(v, self.dim)
        return v * (g / norm)

    def __call__(self, layer, inputs):
        object.__setattr__(layer, self.name, self.compute_weight(layer))


def weight_norm(layer, name="weight", dim=0):
    """w = g * v/||v|| with g = ||w|| along `dim` (None -> scalar norm)."""
    from ...core.tensor import Parameter
    if name not in layer._parameters:
        raise ValueError(f"weight_norm: layer has no parameter {name!r}")
    for h in layer._forward_pre_hooks.values():
        if isinstance(h, _WeightNorm) and h.name == name:
            raise RuntimeError(f"weight_norm already applied to {name!r}")
    w = layer._parameters.pop(name)
    fn = _WeightNorm(name, dim)
    g = _norm_except_dim(w, dim)
    layer.add_parameter(name + "_g", Parameter(g._value))
    layer.add_parameter(name + "_v", Parameter(w._value))
    handle = layer.register_forward_pre_hook(fn)
    fn._handle = handle
    fn(layer, None)          # effective weight available before 1st forward
    return layer


def remove_weight_norm(layer, name="weight"):
    from ...core.tensor import Parameter
    for key, h in list(layer._forward_pre_hooks.items()):
        if isinstance(h, _WeightNorm) and h.name == name:
            w = h.compute_weight(layer)
            del layer._forward_pre_hooks[key]
            del layer._parameters[name + "_g"]
            del layer._parameters[name + "_v"]
            layer.__dict__.pop(name, None)
            layer.add_parameter(name, Parameter(w._value))
            return layer
    raise ValueError(f"weight_norm of {name!r} not found in {layer}")


def _spectral_mat(w_arr, dim):
    """Matricize with `dim` leading (reshape target of the power iteration)."""
    import jax.numpy as jnp
    import numpy as np
    xp = np if isinstance(w_arr, np.ndarray) else jnp
    if dim != 0:
        perm = (dim,) + tuple(i for i in range(w_arr.ndim) if i != dim)
        w_arr = xp.transpose(w_arr, perm)
    return w_arr.reshape(w_arr.shape[0], -1)


def spectral_normalize(w, u, *, dim, n_power_iterations, eps):
    """Shared core of nn.utils.spectral_norm and nn.SpectralNorm: run the
    power iteration HOST-SIDE on the current value (u/v are no-grad
    persistent state, as in the reference op), then divide the weight by
    sigma = u^T W v inside the traced graph so gradients flow through W.
    Returns (normalized_tensor, new_u, new_v)."""
    import jax.numpy as jnp
    import numpy as np
    from ...ops._dispatch import run_op

    wm = np.asarray(_spectral_mat(np.asarray(w._value), dim), dtype=np.float32)
    uv = np.asarray(u, dtype=np.float32)
    vv = None
    for _ in range(max(n_power_iterations, 1)):
        vv = wm.T @ uv
        vv = vv / max(float(np.linalg.norm(vv)), eps)
        uv = wm @ vv
        uv = uv / max(float(np.linalg.norm(uv)), eps)
    uc, vc = jnp.asarray(uv), jnp.asarray(vv)

    def f(wa):
        m = _spectral_mat(wa.astype(jnp.float32), dim)
        sigma = uc @ (m @ vc)
        return (wa.astype(jnp.float32) / sigma).astype(wa.dtype)

    return run_op(f, [w], "spectral_norm"), uv, vv


class _SpectralNorm:
    def __init__(self, name, n_power_iterations, eps, dim):
        self.name = name
        self.n = n_power_iterations
        self.eps = eps
        self.dim = dim

    def _mat(self, w_arr):
        return _spectral_mat(w_arr, self.dim)

    def compute_weight(self, layer):
        w = getattr(layer, self.name + "_orig")
        u = getattr(layer, "_" + self.name + "_u")
        out, new_u, _ = spectral_normalize(
            w, u, dim=self.dim, n_power_iterations=self.n, eps=self.eps)
        object.__setattr__(layer, "_" + self.name + "_u", new_u)
        return out

    def __call__(self, layer, inputs):
        object.__setattr__(layer, self.name, self.compute_weight(layer))


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """w / sigma_max(w) via power iteration (spectral_norm_hook.py:131)."""
    from ...core.tensor import Parameter
    import numpy as np
    if name not in layer._parameters:
        raise ValueError(f"spectral_norm: layer has no parameter {name!r}")
    if dim is None:
        # reference spectral_norm_hook: dim=1 for Linear and ConvTranspose
        # (their out-axis is second), dim=0 otherwise — by isinstance so
        # subclasses resolve correctly
        from ..layer.common import Linear
        try:
            from ..layer.conv import (Conv1DTranspose, Conv2DTranspose,
                                      Conv3DTranspose)
            transposed = (Conv1DTranspose, Conv2DTranspose, Conv3DTranspose)
        except ImportError:
            transposed = ()
        dim = 1 if isinstance(layer, (Linear,) + transposed) else 0
    w = layer._parameters.pop(name)
    fn = _SpectralNorm(name, n_power_iterations, eps, dim)
    layer.add_parameter(name + "_orig", Parameter(w._value))
    h = int(np.asarray(fn._mat(w._value)).shape[0])
    u0 = np.random.RandomState(0).randn(h).astype(np.float32)  # tpu-lint: disable=stdlib-random (fixed-seed host init, runs once)
    object.__setattr__(layer, "_" + name + "_u", u0 / np.linalg.norm(u0))
    handle = layer.register_forward_pre_hook(fn)
    fn._handle = handle
    fn(layer, None)
    return layer
