"""paddle.nn parity namespace."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer.layers import Layer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401

from . import utils  # noqa: F401
