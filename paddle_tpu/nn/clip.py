"""Gradient clipping.

Reference parity: `python/paddle/fluid/clip.py` (ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Operates on (param, grad) pairs like
the reference; grads here are raw jax arrays stored on `param.grad`.
"""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip(self, params_grads):
        return [(p, jnp.clip(g, self.min, self.max) if g is not None else None)
                for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, None))
                continue
            n = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, (g * factor.astype(g.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _clip(self, params_grads):
        sq = [jnp.sum(g.astype(jnp.float32) ** 2) for p, g in params_grads
              if g is not None and getattr(p, "need_clip", True)]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        factor = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, g * factor.astype(g.dtype)))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style utility over .grad (paddle.nn.utils.clip_grad_norm_)."""
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return 0.0
    if norm_type == float("inf"):
        total = max(jnp.max(jnp.abs(p.grad)) for p in params)
    else:
        total = jnp.power(sum(jnp.sum(jnp.abs(p.grad.astype(jnp.float32)) ** norm_type)
                              for p in params), 1.0 / norm_type)
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        p.grad = p.grad * factor.astype(p.grad.dtype)
    return total
