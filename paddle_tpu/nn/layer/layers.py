"""nn.Layer — module base class.

Reference parity: `python/paddle/fluid/dygraph/layers.py:82` (Layer) with
`__call__` at `:916` (pre-hooks → forward → post-hooks), parameter and
sublayer registries, buffers, state_dict/set_state_dict, train/eval.
"""
from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ...core.dtype import convert_dtype, get_default_dtype
from ...core.tensor import Parameter, Tensor

__all__ = ["Layer"]


class _HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks, self._key = hooks, key

    def remove(self):
        self._hooks.pop(self._key, None)


class _CallDepth(threading.local):
    def __init__(self):
        self.depth = 0


_LAYER_CALL_DEPTH = _CallDepth()


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ---- attribute routing ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter) and params is not None:
            layers.pop(name, None)
            buffers.pop(name, None)
            self.__dict__.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer) and layers is not None:
            params.pop(name, None)
            buffers.pop(name, None)
            self.__dict__.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            if params is not None and name in params and value is None:
                params.pop(name)
            if layers is not None and name in layers and value is None:
                layers.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ---- registration ----
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self.__dict__.pop(name, None)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .. import initializer as I
        dt = convert_dtype(dtype) or self._dtype
        init = default_initializer
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        else:
            # set_global_initializer overrides LAYER-BUILTIN defaults (the
            # reference contract: user-specified ParamAttr initializers
            # still win, the layers' own defaults do not)
            gw, gb = I._GLOBAL_INITIALIZER
            g = gb if is_bias else gw
            if g is not None:
                init = g
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        p = Parameter(jnp.zeros([int(s) for s in shape], dtype=dt))
        init(p)
        if attr is not None:
            lr = getattr(attr, "learning_rate", None)
            if lr is not None:
                p.optimize_attr["learning_rate"] = lr
            if getattr(attr, "trainable", True) is False:
                p.stop_gradient = True
                p.trainable = False
        return p

    # ---- traversal ----
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        yield from self._named_parameters_impl(prefix, include_sublayers, seen)

    def _named_parameters_impl(self, prefix, include_sublayers, seen):
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer._named_parameters_impl(sub_prefix, True, seen)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn: Callable):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def full_name(self):
        return self._name_scope

    # ---- modes ----
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ---- hooks ----
    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return _HookRemoveHelper(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return _HookRemoveHelper(self._forward_post_hooks, key)

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            shortname = name.rsplit(".", 1)[-1]
            if shortname not in self._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)  # tpu-lint: disable=host-sync,lazy-sync (host-side state load, not a hot loop)
            tgt = own[k]
            if tuple(arr.shape) != tuple(tgt.shape):
                raise ValueError(f"shape mismatch for {k}: {arr.shape} vs {tuple(tgt.shape)}")
            tgt._value = jnp.asarray(arr, dtype=tgt._value.dtype)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for p in self.parameters():
                if np.issubdtype(p.dtype, np.floating):
                    p._value = p._value.astype(dt)
            for _, b in self.named_buffers():
                if np.issubdtype(b.dtype, np.floating):
                    b._value = b._value.astype(dt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ---- call ----
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # step-chain capture (FLAGS_eager_auto_jit): a TOP-LEVEL layer called
    # repeatedly with the same signature is promoted to its captured
    # static program (jit.to_static machinery) — the repeated per-op tape
    # becomes ONE fwd executable + ONE vjp executable. This is the eager
    # hot loop's answer to the reference's dygraph program-desc caching
    # (imperative/tracer.cc:172): on a remote/tunnel device the per-op
    # RTTs dominate eager stepping, and capture removes all but one.
    _AUTOJIT_THRESHOLD = 3

    def _autojit_try(self, inputs, kwargs):
        from ...core import flags as _flags
        from ...core.tensor import Tensor as _T
        if self.__dict__.get("_autojit_off") or kwargs:
            return None
        if not _flags.flag("eager_auto_jit"):
            return None
        from ...ops import lazy as _lazy
        if _lazy._ACTIVE:
            # the lazy batching executor already collapses the step into
            # O(1) dispatches; capturing on top would fight its segment
            # accounting (and bake pending payloads into a static program)
            return None
        if _LAYER_CALL_DEPTH.depth or not inputs \
                or not all(isinstance(a, _T) for a in inputs):
            return None
        if isinstance(self.__dict__.get("forward"), object) and \
                type(self.__dict__.get("forward")).__name__ == "StaticFunction":
            return None            # explicitly to_static'd already
        import jax as _jax
        if any(isinstance(a._value, _jax.core.Tracer) for a in inputs):
            return None
        for l in self.sublayers(include_self=True):
            if l.training and l._buffers:
                # buffer mutations (BN running stats) are DISCARDED by the
                # functional capture; keep training-mode BN models eager
                return None
            if l._forward_pre_hooks or l._forward_post_hooks:
                # hooks run INSIDE the capture trace, so python side
                # effects (logging, stats) would fire once per compile
                # instead of once per call — keep hooked models eager
                return None
        # key the capture on EVERY sublayer's training flag: toggling one
        # sublayer's train/eval (e.g. model.dropout.eval()) must retrace,
        # not replay the stale program
        sig = (tuple(l.training
                     for l in self.sublayers(include_self=True)),
               tuple((tuple(a.shape), str(a.dtype), a.stop_gradient)
                     for a in inputs))
        state = self.__dict__.setdefault("_autojit_state", {})
        state[sig] = state.get(sig, 0) + 1
        if len(state) > 32:
            state.clear()
        if state[sig] < self._AUTOJIT_THRESHOLD:
            return None
        sf = self.__dict__.get("_autojit_sf")
        if sf is None:
            from ...jit.to_static import StaticFunction
            sf = StaticFunction(type(self).forward.__get__(self), layer=self)
            self.__dict__["_autojit_sf"] = sf
        return sf

    def __call__(self, *inputs, **kwargs):
        sf = self._autojit_try(inputs, kwargs)
        if sf is not None:
            try:
                return sf(*inputs, **kwargs)
            except Exception:
                # any capture failure (untraceable control flow, exotic
                # outputs) permanently reverts this layer to eager
                self.__dict__["_autojit_off"] = True
        _LAYER_CALL_DEPTH.depth += 1
        try:
            for hook in list(self._forward_pre_hooks.values()):
                result = hook(self, inputs)
                if result is not None:
                    inputs = result if isinstance(result, tuple) else (result,)
            outputs = self.forward(*inputs, **kwargs)
            for hook in list(self._forward_post_hooks.values()):
                result = hook(self, inputs, outputs)
                if result is not None:
                    outputs = result
        finally:
            _LAYER_CALL_DEPTH.depth -= 1
        return outputs

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def extra_repr(self):
        return ""
