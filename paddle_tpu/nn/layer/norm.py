"""Norm layers. Reference parity: `python/paddle/nn/layer/norm.py`."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum, self.epsilon = momentum, epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self.momentum,
                            epsilon=self.epsilon, data_format=self.data_format,
                            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, cross-replica BN stats ride the data-parallel mesh axis inside
    jitted programs (GSPMD inserts the all-reduce); eager single-host behaves
    like BatchNorm. Parity: `nn/layer/norm.py` SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            new.weight, new.bias = layer.weight, layer.bias
            new._buffers = layer._buffers
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            self.normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups, self.num_channels = num_groups, num_channels
        self.epsilon, self.data_format = epsilon, data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight, self.bias,
                            self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.epsilon, self.data_format = epsilon, data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self.epsilon,
                               data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Standalone spectral normalization module: forward(weight) returns
    weight / sigma_max estimated by `power_iters` rounds of power
    iteration on persistent u/v buffers (`python/paddle/nn/layer/norm.py`
    SpectralNorm over spectral_norm_op)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        import numpy as _np
        self._dim, self._power_iters, self._eps = dim, power_iters, eps
        h = int(weight_shape[dim])
        w = int(_np.prod(weight_shape)) // h
        rng = _np.random.RandomState(0)
        u = rng.randn(h).astype("float32")
        v = rng.randn(w).astype("float32")
        self._u = u / max(float(_np.linalg.norm(u)), eps)
        self._v = v / max(float(_np.linalg.norm(v)), eps)

    def forward(self, weight):
        from ...ops._dispatch import ensure_tensor
        from ..utils import spectral_normalize
        weight = ensure_tensor(weight)
        out, self._u, self._v = spectral_normalize(
            weight, self._u, dim=self._dim,
            n_power_iterations=self._power_iters, eps=self._eps)
        return out
