"""Common layers: Linear, Dropout, Embedding, Flatten, Upsample, Pad, …

Reference parity: `python/paddle/nn/layer/common.py`.
"""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, W: [in_features, out_features] (paddle convention)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=None if _has_init(weight_attr) else I.XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], attr=None if bias_attr in (None, True) else bias_attr,
            is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


def _has_init(attr):
    return attr is not None and getattr(attr, "initializer", None) is not None


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.sparse = sparse
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=None if _has_init(weight_attr) else I.Normal(0.0, 1.0))
        if padding_idx is not None:
            import jax.numpy as jnp
            self.weight._value = self.weight._value.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx,
                           sparse=self.sparse)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    pass


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([out_features, in1_features, in2_features],
                                            attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Fold(Layer):
    """col2im layer over F.fold (`python/paddle/nn/layer/common.py` Fold)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        from ..functional.common import fold
        return fold(x, *self._args)


class PairwiseDistance(Layer):
    """p-norm distance between row pairs
    (`python/paddle/nn/layer/distance.py` PairwiseDistance)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._p, self._eps, self._keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ...ops._dispatch import ensure_tensor, run_op
        import jax.numpy as jnp
        x, y = ensure_tensor(x), ensure_tensor(y)
        p, eps, keep = self._p, self._eps, self._keepdim

        def f(a, b):
            d = a - b + eps
            return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p), axis=-1,
                                     keepdims=keep), 1.0 / p)

        return run_op(f, [x, y], "pairwise_distance")
