"""Conv layers. Reference parity: `python/paddle/nn/layer/conv.py`."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _ntuple(v, n):
    return [v] * n if isinstance(v, int) else list(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        self.in_channels, self.out_channels = in_channels, out_channels
        self.kernel_size = _ntuple(kernel_size, nd)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.groups, self.data_format = groups, data_format
        self.output_padding = output_padding
        self._nd = nd
        if transpose:
            wshape = [in_channels, out_channels // groups] + self.kernel_size
        else:
            wshape = [out_channels, in_channels // groups] + self.kernel_size
        fan_in = (in_channels // groups) * 1
        for k in self.kernel_size:
            fan_in *= k
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in)
            if weight_attr is None or getattr(weight_attr, "initializer", None) is None
            else None)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, kernel_size={self.kernel_size}, "
                f"stride={self.stride}, padding={self.padding}")


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation,
                                  output_size, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation,
                                  output_size, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation,
                                  output_size, self.data_format)
