"""Recurrent layers: SimpleRNN/LSTM/GRU cells and multi-layer wrappers.

Reference parity: `python/paddle/nn/layer/rnn.py` — RNNCellBase (:139),
SimpleRNNCell (:263), LSTMCell (:399), GRUCell (:556), RNN (:707),
BiRNN (:782), RNNBase (:861), SimpleRNN/LSTM/GRU (:1105/:1212/:1323);
sequence-length masking semantics from `fluid/layers/rnn.py:517`
(_maybe_copy: padded steps carry the previous state through).

TPU-first design: where the reference dispatches one fused cudnn `rnn` op
per forward (`_cudnn_impl`, rnn.py:1002) or falls back to a Python
time-step loop, here the entire sequence sweep of a builtin cell is ONE
`lax.scan` traced as a single autograd op — XLA unrolls nothing, the MXU
sees one [B, I]x[I, G*H] matmul per step, and backward is the scan's VJP
(a reverse scan), so eager mode records one tape node per layer-direction
instead of O(T) nodes. Custom cells still get the step-loop fallback.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._dispatch import ensure_tensor, run_op
from .. import functional as F
from .. import initializer as I
from .container import LayerList
from .layers import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


def split_states(states, bidirectional=False, state_components=1):
    """[L*D, B, H]-stacked states -> per-layer (per-direction) structures.

    Reference parity: `nn/layer/rnn.py:44`.
    """
    from ...ops.manipulation import unstack
    if state_components == 1:
        states = unstack(states)
    else:
        comps = [unstack(c) for c in states]
        states = [tuple(c[i] for c in comps) for i in range(len(comps[0]))]
    if not bidirectional:
        return states
    return [(states[2 * i], states[2 * i + 1]) for i in range(len(states) // 2)]


def concat_states(states, bidirectional=False, state_components=1):
    """Inverse of split_states. Reference parity: `nn/layer/rnn.py:97`."""
    if bidirectional:
        flat = []
        for pair in states:
            flat.extend(pair)
    else:
        flat = list(states)
    if state_components == 1:
        return _stack(flat)
    comps = []
    for c in range(state_components):
        comps.append(_stack([s[c] for s in flat]))
    return tuple(comps)


def _stack(tensors):
    from ...ops.manipulation import stack
    return stack(tensors, axis=0)


class RNNCellBase(Layer):
    """Base for cells: provides zero initial states from a batch reference.

    Reference parity: `nn/layer/rnn.py:139`.
    """

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        shape = shape if shape is not None else self.state_shape
        ref = batch_ref
        while isinstance(ref, (list, tuple)):
            ref = ref[0]
        batch = ref.shape[batch_dim_idx]
        dtype = dtype or ref.dtype

        def make(s):
            return Tensor(jnp.full((batch,) + tuple(s), init_value,
                                   dtype=dtype))

        if shape and isinstance(shape[0], (list, tuple)):
            return tuple(make(s) for s in shape)
        return make(shape)


# ---- pure per-step transition functions (scanned AND single-stepped) ----

def _simple_rnn_step(x, hs, w_ih, w_hh, b_ih, b_hh, activation):
    h, = hs
    g = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    h = jnp.tanh(g) if activation == "tanh" else jax.nn.relu(g)
    return h, (h,)


def _lstm_step(x, hs, w_ih, w_hh, b_ih, b_hh, _activation=None):
    h, c = hs
    g = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    i, f, cand, o = jnp.split(g, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(cand)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, (h, c)


def _gru_step(x, hs, w_ih, w_hh, b_ih, b_hh, _activation=None):
    h, = hs
    xg = x @ w_ih.T
    if b_ih is not None:
        xg = xg + b_ih
    hg = h @ w_hh.T
    if b_hh is not None:
        hg = hg + b_hh
    x_r, x_z, x_c = jnp.split(xg, 3, axis=-1)
    h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(x_r + h_r)
    z = jax.nn.sigmoid(x_z + h_z)
    cand = jnp.tanh(x_c + r * h_c)  # reset gate applied after the matmul
    h = z * h + (1.0 - z) * cand
    return h, (h,)


def _unpack_weights(arrs, flags):
    """(w_ih, w_hh, b_ih|None, b_hh|None) from the flat array list."""
    has_bih, has_bhh = flags
    it = iter(arrs)
    w_ih, w_hh = next(it), next(it)
    b_ih = next(it) if has_bih else None
    b_hh = next(it) if has_bhh else None
    return w_ih, w_hh, b_ih, b_hh


class _BuiltinCell(RNNCellBase):
    """Shared weight plumbing for the three builtin cells."""

    _gates = 1
    _step = staticmethod(_simple_rnn_step)
    _state_components = 1

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError(
                f"hidden_size of {type(self).__name__} must be greater than "
                f"0, but now equals to {hidden_size}")
        std = 1.0 / math.sqrt(hidden_size)
        g = self._gates
        self.weight_ih = self.create_parameter(
            (g * hidden_size, input_size), weight_ih_attr,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            (g * hidden_size, hidden_size), weight_hh_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter(
            (g * hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter(
            (g * hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = "tanh"

    def _weight_tensors(self):
        ws = [self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            ws.append(self.bias_ih)
        if self.bias_hh is not None:
            ws.append(self.bias_hh)
        return ws

    def _bias_flags(self):
        return (self.bias_ih is not None, self.bias_hh is not None)

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        flat_states = list(states) if isinstance(states, (list, tuple)) \
            else [states]
        flat_states = [ensure_tensor(s) for s in flat_states]
        # closure captures hashables only, so eager dispatch can cache the
        # traced (forward, vjp) pair across steps (core/autograd.py)
        step, act = self._step, self.activation
        n_state, flags = len(flat_states), self._bias_flags()

        def fused(x, *rest):
            hs = rest[:n_state]
            w = _unpack_weights(rest[n_state:], flags)
            _, new = step(x, hs, *w, act)
            return tuple(new)

        outs = run_op(fused, [inputs, *flat_states, *self._weight_tensors()],
                      type(self).__name__)
        outs = outs if isinstance(outs, tuple) else (outs,)
        if self._state_components == 1:
            return outs[0], outs[0]
        return outs[0], tuple(outs)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class SimpleRNNCell(_BuiltinCell):
    r"""Elman cell: h_t = act(W_ih x_t + b_ih + W_hh h_{t-1} + b_hh).

    Reference parity: `nn/layer/rnn.py:263`.
    """

    _gates = 1
    _step = staticmethod(_simple_rnn_step)
    _state_components = 1

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)
        if activation not in ("tanh", "relu"):
            raise ValueError(
                "activation for SimpleRNNCell should be tanh or relu, "
                f"but get {activation}")
        self.activation = activation

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        s = f"{self.input_size}, {self.hidden_size}"
        if self.activation != "tanh":
            s += f", activation={self.activation}"
        return s


class LSTMCell(_BuiltinCell):
    r"""LSTM cell; weights hold the i|f|g|o gate concatenation.

    Reference parity: `nn/layer/rnn.py:399` (gate order at :536-539).
    """

    _gates = 4
    _step = staticmethod(_lstm_step)
    _state_components = 2

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(_BuiltinCell):
    r"""GRU cell; weights hold the r|z|c gate concatenation.

    Reference parity: `nn/layer/rnn.py:556` (reset-after-matmul at :681).
    """

    _gates = 3
    _step = staticmethod(_gru_step)
    _state_components = 1

    @property
    def state_shape(self):
        return (self.hidden_size,)


def _scan_rnn(step, x, states, weights, *, activation, time_major,
              is_reverse, seq_len):
    """One whole-sequence sweep as a single lax.scan (pure arrays in/out)."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)          # -> [T, B, I]
    T = x.shape[0]
    mask = None
    if seq_len is not None:
        t_idx = jnp.arange(T, dtype=jnp.int32)[:, None]
        mask = (t_idx < seq_len[None, :].astype(jnp.int32)).astype(x.dtype)
    if is_reverse:
        x = jnp.flip(x, axis=0)
        mask = jnp.flip(mask, axis=0) if mask is not None else None

    def body(carry, inp):
        if mask is None:
            xt = inp
            out, new = step(xt, carry, *weights, activation)
        else:
            xt, m = inp
            out, new = step(xt, carry, *weights, activation)
            m = m[:, None]
            new = tuple(m * n + (1.0 - m) * o for n, o in zip(new, carry))
        return new, out

    xs = x if mask is None else (x, mask)
    final, outs = jax.lax.scan(body, tuple(states), xs)
    if is_reverse:
        outs = jnp.flip(outs, axis=0)
    if not time_major:
        outs = jnp.swapaxes(outs, 0, 1)
    return outs, final


class RNN(Layer):
    """Run a cell over a sequence.

    Reference parity: `nn/layer/rnn.py:707` + `fluid/layers/rnn.py:437`
    (padded steps pass the previous state through; outputs are the raw per-
    step outputs). Builtin cells take the fused single-scan path; arbitrary
    cells fall back to a per-step loop like `_rnn_dynamic_graph`
    (`fluid/layers/rnn.py:529`).
    """

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if isinstance(self.cell, _BuiltinCell) and not kwargs:
            return self._scan_forward(inputs, initial_states, sequence_length)
        return self._loop_forward(inputs, initial_states, sequence_length,
                                  **kwargs)

    def _scan_forward(self, inputs, initial_states, sequence_length):
        cell = self.cell
        inputs = ensure_tensor(inputs)
        if initial_states is None:
            initial_states = cell.get_initial_states(
                inputs, cell.state_shape,
                batch_dim_idx=1 if self.time_major else 0)
        flat_states = list(initial_states) if isinstance(
            initial_states, (list, tuple)) else [initial_states]
        flat_states = [ensure_tensor(s) for s in flat_states]
        n_state = len(flat_states)
        seq = None
        if sequence_length is not None:
            seq = sequence_length._value if isinstance(
                sequence_length, Tensor) else jnp.asarray(sequence_length)
        # hashable-only closure (except seq) -> dispatch-cacheable sweep
        step, act, flags = cell._step, cell.activation, cell._bias_flags()
        time_major, is_reverse = self.time_major, self.is_reverse

        def sweep(x, *rest):
            hs = rest[:n_state]
            w = _unpack_weights(rest[n_state:], flags)
            outs, final = _scan_rnn(step, x, hs, w, activation=act,
                                    time_major=time_major,
                                    is_reverse=is_reverse, seq_len=seq)
            return (outs,) + tuple(final)

        res = run_op(sweep, [inputs, *flat_states, *cell._weight_tensors()],
                     f"rnn_{type(cell).__name__}")
        outputs = res[0]
        finals = res[1:]
        if cell._state_components == 1:
            return outputs, finals[0]
        return outputs, tuple(finals)

    def _loop_forward(self, inputs, initial_states, sequence_length, **kwargs):
        cell = self.cell
        inputs = ensure_tensor(inputs)
        time_axis = 0 if self.time_major else 1
        T = inputs.shape[time_axis]
        if initial_states is None:
            initial_states = cell.get_initial_states(
                inputs, batch_dim_idx=1 if self.time_major else 0)
        states = initial_states
        mask_np = None
        if sequence_length is not None:
            seq = sequence_length._value if isinstance(
                sequence_length, Tensor) else jnp.asarray(sequence_length)
            mask_np = (jnp.arange(T)[:, None] < seq[None, :]).astype(
                inputs.dtype)
        order = range(T - 1, -1, -1) if self.is_reverse else range(T)
        outs = []
        for t in order:
            xt = run_op(lambda a, t=t: jnp.take(a, t, axis=time_axis),
                        [inputs], "slice")
            out, new_states = cell(xt, states, **kwargs)
            if mask_np is not None:
                m = Tensor(mask_np[t][:, None])
                flat_new = new_states if isinstance(new_states, tuple) \
                    else (new_states,)
                flat_old = states if isinstance(states, tuple) else (states,)
                merged = tuple(n * m + o * (1.0 - m)
                               for n, o in zip(flat_new, flat_old))
                new_states = merged if isinstance(new_states, tuple) \
                    else merged[0]
            states = new_states
            outs.append(out)
        if self.is_reverse:
            outs.reverse()
        outputs = run_op(lambda *xs: jnp.stack(xs, axis=time_axis),
                         outs, "stack")
        return outputs, states


class BiRNN(Layer):
    """Forward + backward sweeps, outputs concatenated on the last axis.

    Reference parity: `nn/layer/rnn.py:782`.
    """

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, final_fw = self.rnn_fw(inputs, states_fw, sequence_length,
                                       **kwargs)
        out_bw, final_bw = self.rnn_bw(inputs, states_bw, sequence_length,
                                       **kwargs)
        outputs = run_op(lambda a, b: jnp.concatenate([a, b], axis=-1),
                         [out_fw, out_bw], "concat")
        return outputs, (final_fw, final_bw)


class RNNBase(LayerList):
    """Multi-layer, optionally bidirectional recurrent network.

    Reference parity: `nn/layer/rnn.py:861`; `flatten_parameters`
    (cudnn weight coalescing, :948) is a no-op here — XLA owns layout.
    """

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, activation=None):
        super().__init__()
        bidirectional_list = ("bidirectional", "bidirect")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.dropout = dropout
        self.num_directions = 2 if direction in bidirectional_list else 1
        self.time_major = time_major
        self.num_layers = num_layers
        self.state_components = 2 if mode == "LSTM" else 1

        kwargs = {
            "weight_ih_attr": weight_ih_attr,
            "weight_hh_attr": weight_hh_attr,
            "bias_ih_attr": bias_ih_attr,
            "bias_hh_attr": bias_hh_attr,
        }
        if mode == "LSTM":
            rnn_cls = LSTMCell
        elif mode == "GRU":
            rnn_cls = GRUCell
        else:
            rnn_cls = SimpleRNNCell
            kwargs["activation"] = activation or "tanh"

        if direction == "forward":
            cell = rnn_cls(input_size, hidden_size, **kwargs)
            self.append(RNN(cell, False, time_major))
            for _ in range(1, num_layers):
                cell = rnn_cls(hidden_size, hidden_size, **kwargs)
                self.append(RNN(cell, False, time_major))
        elif direction in bidirectional_list:
            cell_fw = rnn_cls(input_size, hidden_size, **kwargs)
            cell_bw = rnn_cls(input_size, hidden_size, **kwargs)
            self.append(BiRNN(cell_fw, cell_bw, time_major))
            for _ in range(1, num_layers):
                cell_fw = rnn_cls(2 * hidden_size, hidden_size, **kwargs)
                cell_bw = rnn_cls(2 * hidden_size, hidden_size, **kwargs)
                self.append(BiRNN(cell_fw, cell_bw, time_major))
        else:
            raise ValueError(
                "direction should be forward or bidirect (or bidirectional), "
                f"received direction = {direction}")

        # Flat aliases (weight_ih_l0, bias_hh_l1_reverse, ...) matching the
        # reference's exposed attribute names; stored via object.__setattr__
        # so state_dict does not double-count the cells' parameters.
        for layer in range(num_layers):
            for d in range(self.num_directions):
                suffix = "_reverse" if d == 1 else ""
                wrapper = self[layer]
                cell = (wrapper.cell_fw if d == 0 else wrapper.cell_bw) \
                    if self.num_directions == 2 else wrapper.cell
                for wname in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"):
                    p = getattr(cell, wname, None)
                    if p is not None:
                        object.__setattr__(
                            self, f"{wname}_l{layer}{suffix}", p)

    def flatten_parameters(self):
        """cudnn weight-coalescing hook — nothing to do under XLA."""

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_index = 1 if self.time_major else 0
        if initial_states is None:
            L = self.num_layers * self.num_directions
            batch = inputs.shape[batch_index]
            z = Tensor(jnp.zeros((L, batch, self.hidden_size),
                                 dtype=ensure_tensor(inputs).dtype))
            initial_states = tuple(
                Tensor(z._value) for _ in range(self.state_components))
            initial_states = initial_states if self.state_components > 1 \
                else initial_states[0]
        if not isinstance(initial_states, (list, tuple)):
            initial_states = (initial_states,)
        elif self.state_components > 1:
            initial_states = tuple(initial_states)

        states = split_states(
            tuple(ensure_tensor(s) for s in initial_states)
            if self.state_components > 1 else ensure_tensor(initial_states[0]),
            self.num_directions == 2, self.state_components)

        final_states = []
        outputs = inputs
        for i, rnn_layer in enumerate(self):
            if i > 0:
                outputs = F.dropout(outputs, self.dropout,
                                    training=self.training,
                                    mode="upscale_in_train")
            outputs, final = rnn_layer(outputs, states[i], sequence_length)
            final_states.append(final)

        final_states = concat_states(final_states, self.num_directions == 2,
                                     self.state_components)
        return outputs, final_states

    def extra_repr(self):
        s = f"{self.input_size}, {self.hidden_size}"
        if self.num_layers != 1:
            s += f", num_layers={self.num_layers}"
        if self.time_major:
            s += f", time_major={self.time_major}"
        if self.dropout != 0:
            s += f", dropout={self.dropout}"
        return s


class SimpleRNN(RNNBase):
    """Multilayer Elman network. Reference parity: `nn/layer/rnn.py:1105`."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        if activation == "tanh":
            mode = "RNN_TANH"
        elif activation == "relu":
            mode = "RNN_RELU"
        else:
            raise ValueError(f"Unknown activation '{activation}'")
        self.activation = activation
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr, activation=activation)


class LSTM(RNNBase):
    """Multilayer LSTM. Reference parity: `nn/layer/rnn.py:1212`."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class GRU(RNNBase):
    """Multilayer GRU. Reference parity: `nn/layer/rnn.py:1323`."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
