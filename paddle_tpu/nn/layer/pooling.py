"""Pooling layers. Reference parity: `python/paddle/nn/layer/pooling.py`."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class _Pool(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format=None, **kw):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.ceil_mode, self.data_format = ceil_mode, data_format


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode, data_format=self.data_format or "NCL")


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode, data_format=self.data_format or "NCHW")


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode, data_format=self.data_format or "NCDHW")


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            data_format=self.data_format or "NCL")


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode, data_format=self.data_format or "NCHW")


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode, data_format=self.data_format or "NCDHW")


class _AdaptivePool(Layer):
    def __init__(self, output_size, data_format=None, return_mask=False, name=None):
        super().__init__()
        self.output_size, self.data_format = output_size, data_format
        self.return_mask = return_mask


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format or "NCHW")


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format or "NCDHW")


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        from ..functional.pooling import max_unpool1d
        k, s, p, df, os_ = self._a
        return max_unpool1d(x, indices, k, s, p, df, os_)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        from ..functional.pooling import max_unpool2d
        k, s, p, df, os_ = self._a
        return max_unpool2d(x, indices, k, s, p, df, os_)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        from ..functional.pooling import max_unpool3d
        k, s, p, df, os_ = self._a
        return max_unpool3d(x, indices, k, s, p, df, os_)
