"""Loss functionals.

Reference parity: `python/paddle/nn/functional/loss.py` (cross_entropy with
soft/hard labels + ignore_index, mse, l1, nll, bce, kl_div, smooth_l1,
margin losses, ctc excluded this round).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._dispatch import ensure_tensor, run_op


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    # label rides as a positional input (not a closure capture) so the
    # eager dispatch cache can key this op by its static config alone
    def f(logits, lv, *rest):
        def _logp():
            return jax.nn.log_softmax(logits, axis=axis) if use_softmax else \
                jnp.log(jnp.maximum(logits, 1e-30))
        n_class = logits.shape[axis]
        if soft_label:
            logp = _logp()
            tgt = lv.astype(logp.dtype)
            if label_smoothing > 0:
                tgt = tgt * (1 - label_smoothing) + label_smoothing / n_class
            per = -jnp.sum(tgt * logp, axis=axis)
            if rest:
                w = rest[0]
                cls_w = jnp.sum(tgt * w.reshape((1,) * (logp.ndim - 1) + (-1,)), axis=axis)
                per = per * cls_w
            return _reduce(per, reduction)
        ids = lv.astype(jnp.int32)
        squeeze = False
        if ids.ndim == logits.ndim:  # [N,1] style labels
            ids = jnp.squeeze(ids, axis=axis)
            squeeze = True
        safe = jnp.where(ids == ignore_index, 0, ids)
        if use_softmax:
            # -logp[target] = lse(logits) - logits[target]: never materializes
            # the [.., n_class] log-prob tensor (at a 30k vocab that's a
            # 250MB HBM round-trip per step the MXU sits idle for)
            lse = jax.scipy.special.logsumexp(logits, axis=axis)
            took = jnp.take_along_axis(logits, jnp.expand_dims(safe, axis),
                                       axis=axis)
            per = lse - jnp.squeeze(took, axis)
            if label_smoothing > 0:
                smooth = lse - jnp.mean(logits, axis=axis)
                per = (1 - label_smoothing) * per + label_smoothing * smooth
        else:
            lp = _logp()
            picked = jnp.take_along_axis(lp, jnp.expand_dims(safe, axis),
                                         axis=axis)
            per = -jnp.squeeze(picked, axis)
            if label_smoothing > 0:
                smooth = -jnp.mean(lp, axis=axis)
                per = (1 - label_smoothing) * per + label_smoothing * smooth
        mask = (ids != ignore_index)
        if rest:
            w = rest[0]
            per = per * jnp.take(w, safe)
        per = jnp.where(mask, per, 0.0)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(mask.astype(per.dtype)), 1.0)
            if rest:
                denom = jnp.maximum(
                    jnp.sum(jnp.where(mask, jnp.take(rest[0], safe), 0.0)), 1e-12)
            return jnp.sum(per) / denom
        if reduction == "sum":
            return jnp.sum(per)
        if squeeze:  # [N,1]-style labels: per-sample loss keeps their shape
            per = jnp.expand_dims(per, axis)
        return per

    ins = [input, label] + ([ensure_tensor(weight)] if weight is not None else [])
    return run_op(f, ins, "cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return run_op(lambda a, b: _reduce(jnp.square(a - b), reduction),
                  [ensure_tensor(input), ensure_tensor(label)], "mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return run_op(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                  [ensure_tensor(input), ensure_tensor(label)], "l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = a - b
        ad = jnp.abs(d)
        v = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(v, reduction)

    return run_op(f, [ensure_tensor(input), ensure_tensor(label)], "smooth_l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    ids = label._value.astype(jnp.int32)

    def f(logp, *rest):
        safe = jnp.where(ids == ignore_index, 0, ids)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        per = -jnp.squeeze(picked, 1)
        mask = ids != ignore_index
        if rest:
            per = per * jnp.take(rest[0], safe)
        per = jnp.where(mask, per, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.take(rest[0], safe) * mask) if rest else \
                jnp.maximum(jnp.sum(mask.astype(per.dtype)), 1.0)
            return jnp.sum(per) / denom
        return _reduce(per, reduction) if reduction != "mean" else per

    ins = [input] + ([ensure_tensor(weight)] if weight is not None else [])
    return run_op(f, ins, "nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *rest):
        eps = 1e-12
        v = -(y * jnp.log(jnp.maximum(p, eps)) + (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if rest:
            v = v * rest[0]
        return _reduce(v, reduction)

    ins = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        ins.append(ensure_tensor(weight))
    return run_op(f, ins, "bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    pw = ensure_tensor(pos_weight)._value if pos_weight is not None else None

    def f(z, y, *rest):
        # numerically-stable BCE-with-logits
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            logsig = -jax.nn.softplus(-z)
            log1msig = -jax.nn.softplus(z)
            base = -(pw * y * logsig + (1 - y) * log1msig)
        if rest:
            base = base * rest[0]
        return _reduce(base, reduction)

    ins = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        ins.append(ensure_tensor(weight))
    return run_op(f, ins, "bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, tgt):
        v = tgt * (jnp.log(jnp.maximum(tgt, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(v) / logp.shape[0]
        return _reduce(v, reduction)

    return run_op(f, [ensure_tensor(input), ensure_tensor(label)], "kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return run_op(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        [ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)],
        "margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return run_op(
        lambda a, y: _reduce(jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        [ensure_tensor(input), ensure_tensor(label)], "hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        v = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(v, reduction)

    return run_op(f, [ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label)],
                  "cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + epsilon, p), -1), 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + epsilon, p), -1), 1 / p)
        if swap:
            dpn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + epsilon, p), -1), 1 / p)
            dn = jnp.minimum(dn, dpn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return run_op(f, [ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative)],
                  "triplet_margin_loss")


def square_error_cost(input, label):
    return run_op(lambda a, b: jnp.square(a - b),
                  [ensure_tensor(input), ensure_tensor(label)], "square_error_cost")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        v = a_t * jnp.power(1 - p_t, gamma) * ce
        if rest:
            v = v / rest[0]
        return _reduce(v, reduction)

    ins = [ensure_tensor(logit), ensure_tensor(label)]
    if normalizer is not None:
        ins.append(ensure_tensor(normalizer))
    return run_op(f, ins, "sigmoid_focal_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification loss.

    Reference parity: `python/paddle/nn/functional/loss.py:1010` (warpctc
    wrapper — softmax is applied internally, so `log_probs` is UNSCALED
    logits shaped [max_logit_length, batch, num_classes+1]; `reduction`
    'mean' divides each sample's loss by its label length first).

    TPU-first design: instead of the warp-ctc CUDA kernel the forward is
    the standard log-semiring alpha recursion vectorized over (batch,
    extended-label) and scanned over time with `lax.scan`; the backward is
    the scan's VJP, so no hand-written gradient kernel is needed.
    """
    if norm_by_times:
        raise NotImplementedError(
            "norm_by_times rescales gradients only (warpctc semantics); "
            "use reduction='mean' on TPU instead")

    def f(logits):
        lab = labels_v
        T, B, C = logits.shape
        L = lab.shape[1]
        S = 2 * L + 1
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        in_len = jnp.asarray(input_lengths_v, jnp.int32)
        lab_len = jnp.asarray(label_lengths_v, jnp.int32)
        neg_inf = jnp.float32(-1e30)

        # extended label row per sample: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        # skip transition allowed where ext[s] != blank and != ext[s-2]
        ext_m2 = jnp.concatenate(
            [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
        can_skip = (ext != blank) & (ext != ext_m2)

        emit = jnp.take_along_axis(          # [T, B, S] log p(ext[s] | t)
            logp, jnp.broadcast_to(ext[None], (T, B, S)), axis=2)

        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(emit[0, :, 0])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, emit[0, :, 1], neg_inf))

        def step(alpha, inp):
            em, t = inp
            a_m1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_m2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_m2 = jnp.where(can_skip, a_m2, neg_inf)
            stacked = jnp.stack([alpha, a_m1, a_m2], 0)
            new = jax.scipy.special.logsumexp(stacked, axis=0) + em
            # past this sample's input length the alphas freeze
            live = (t < in_len)[:, None]
            return jnp.where(live, new, alpha), None

        ts = jnp.arange(1, T, dtype=jnp.int32)
        alpha, _ = jax.lax.scan(step, alpha0, (emit[1:], ts))

        end = 2 * lab_len            # blank after last label
        a_last = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(end[:, None] - 1, 0), axis=1)[:, 0]
        a_prev = jnp.where(lab_len > 0, a_prev, neg_inf)
        ll = jnp.logaddexp(a_last, a_prev)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len, 1).astype(loss.dtype))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    log_probs = ensure_tensor(log_probs)
    labels_v = ensure_tensor(labels)._value
    input_lengths_v = input_lengths._value if isinstance(
        input_lengths, Tensor) else jnp.asarray(input_lengths)
    label_lengths_v = label_lengths._value if isinstance(
        label_lengths, Tensor) else jnp.asarray(label_lengths)
    # warpctc errors on out-of-range lengths; the clipped take_along_axis
    # below would silently read frozen alpha entries instead (ADVICE r4).
    # Validate HOST-side values only — np.asarray on a device array would
    # add a device->host sync per call to the hot loss path; device-array
    # lengths are trusted (they came from the same device pipeline).
    import numpy as _np
    T_max = int(log_probs._value.shape[0])
    L_max = int(labels_v.shape[1]) if labels_v.ndim > 1 else int(
        labels_v.shape[0])

    def _host_max(v):
        if isinstance(v, (int, list, tuple, _np.ndarray, _np.integer)):
            return int(_np.max(_np.asarray(v)))
        return None

    im, lm = _host_max(input_lengths), _host_max(label_lengths)
    if im is not None and im > T_max:
        raise ValueError(
            f"ctc_loss: input_lengths exceed max_logit_length {T_max}")
    if lm is not None and lm > L_max:
        raise ValueError(
            f"ctc_loss: label_lengths exceed labels length {L_max}")
    return run_op(f, [log_probs], "ctc_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-family margin softmax CE, model-parallel native.

    Reference parity: `python/paddle/nn/functional/loss.py:1107`
    (margin_cross_entropy over `c_margin_cross_entropy` CUDA op). logits are
    cosines (normalized feature x normalized class centers), [N, C_local].
    The target logit becomes cos(m1*theta + m2) - m3, everything scales by
    `scale`, then softmax CE.

    TPU design: under the manual-collective mp regime (shard_map over the
    'mp' axis) the class dimension stays sharded — exactly one pmax (row
    max) and two psums (denominator, picked target logit) cross the mesh,
    the ParallelCrossEntropy pattern; each rank applies the margin only to
    targets it owns (equal class shards per rank). Outside mp it is the
    single-chip op. Gradients flow through the arccos margin transform via
    autodiff (the reference kernel hand-codes the same derivative).
    """
    from jax import lax
    from ...parallel.collective import _in_spmd

    logits, label = ensure_tensor(logits), ensure_tensor(label)
    mp = _in_spmd("mp")

    def f(lg, lb):
        ids = lb.astype(jnp.int32)
        if ids.ndim == lg.ndim:
            ids = jnp.squeeze(ids, -1)
        per = lg.shape[-1]
        if mp:
            local = ids - lax.axis_index("mp") * per
        else:
            local = ids
        in_shard = (local >= 0) & (local < per)
        safe = jnp.where(in_shard, local, 0)
        target = jnp.take_along_axis(lg, safe[:, None], axis=-1)[:, 0]
        # shrink the clip by eps: arccos' = -1/sqrt(1-c^2) is -inf at
        # |c| == 1, and ArcFace training drives target cosines toward 1 —
        # exact 1.0 (guaranteed eventually in bf16) would NaN every grad
        theta = jnp.arccos(jnp.clip(target.astype(jnp.float32),
                                    -1.0 + 1e-6, 1.0 - 1e-6))
        mod = (jnp.cos(margin1 * theta + margin2) - margin3).astype(lg.dtype)
        col = lax.broadcasted_iota(jnp.int32, lg.shape, 1)
        is_target = (col == safe[:, None]) & in_shard[:, None]
        lg2 = jnp.where(is_target, mod[:, None], lg) * scale
        # the max shift is numerical-stability only and cancels in the
        # log-softmax algebra — stop_gradient keeps it out of the vjp
        # (pmax has no differentiation rule, and none is needed)
        if mp:
            vmax = lax.stop_gradient(
                lax.pmax(jnp.max(lax.stop_gradient(lg2), -1, keepdims=True),
                         "mp"))
            ex = jnp.exp(lg2 - vmax)
            denom = lax.psum(jnp.sum(ex, -1, keepdims=True), "mp")
        else:
            vmax = lax.stop_gradient(jnp.max(lg2, -1, keepdims=True))
            ex = jnp.exp(lg2 - vmax)
            denom = jnp.sum(ex, -1, keepdims=True)
        sm = ex / denom
        picked = jnp.where(
            in_shard[:, None],
            jnp.take_along_axis(lg2 - vmax, safe[:, None], axis=-1),
            jnp.zeros((), lg2.dtype))
        if mp:
            picked = lax.psum(picked, "mp")
        loss = jnp.log(denom) - picked                    # [N, 1]
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        return (loss, sm) if return_softmax else loss

    out = run_op(f, [logits, label], "margin_cross_entropy")
    return out


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - 2*|X∩Y| / (|X|+|Y|) per sample, meaned
    (`fluid/layers/nn.py:7195`): label is int class ids [..., 1]."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(a, lb):
        ids = lb.astype(jnp.int32)
        if ids.shape[-1] == 1:
            ids = ids[..., 0]
        oh = jax.nn.one_hot(ids, a.shape[-1], dtype=a.dtype)
        axes = tuple(range(1, a.ndim))
        inse = jnp.sum(a * oh, axis=axes)
        denom = jnp.sum(a, axis=axes) + jnp.sum(oh, axis=axes)
        return jnp.mean(1 - inse * 2 / (denom + epsilon))

    return run_op(f, [input, label], "dice_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    """-y*log(p+eps) - (1-y)*log(1-p+eps) (log_loss_op)."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1.0 - y) * jnp.log(1.0 - p + epsilon)

    return run_op(f, [input, label], "log_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair metric loss (`fluid/layers/loss.py:1666`): soft-label CE over
    the anchor/positive similarity matrix + L2 on the embeddings."""
    anchor, positive, labels = (ensure_tensor(anchor), ensure_tensor(positive),
                                ensure_tensor(labels))

    def f(a, p, lb):
        n = lb.shape[0]
        eq = (lb[:, None] == lb[None, :]).astype(a.dtype)
        soft = eq / jnp.sum(eq, axis=1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) \
            * 0.25 * l2_reg
        sim = a @ p.T
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce_rows = -jnp.sum(soft * logp, axis=-1)      # [N]
        # the reference's reduce_mean(reduce_sum(labels * ce, 0)) is
        # algebraically mean(ce_rows): soft rows sum to 1, so the double
        # sum collapses — skip the O(N^2) reweighting product
        ce = jnp.mean(ce_rows)
        return l2 + ce

    return run_op(f, [anchor, positive, labels], "npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (`nn/functional/loss.py` hsigmoid_loss over
    hierarchical_sigmoid_op; default complete-binary-tree coding from
    matrix_bit_code.h SimpleCode: c = label + num_classes, node(bit) =
    (c >> (bit+1)) - 1, branch(bit) = c & (1 << bit)).

    input [N, D]; weight [num_classes-1, D]; returns [N, 1]. Custom trees
    via path_table/path_code [N, L] (entries < 0 are padding). The bit walk
    is a static loop over max code length with per-sample masks — no
    data-dependent shapes, jits whole."""
    input, label, weight = (ensure_tensor(input), ensure_tensor(label),
                            ensure_tensor(weight))
    extra = []
    if bias is not None:
        extra.append(ensure_tensor(bias))
    pt = ensure_tensor(path_table)._value if path_table is not None else None
    pc = ensure_tensor(path_code)._value if path_code is not None else None

    def f(x, lb, w, *rest):
        b = rest[0] if bias is not None else None
        ids = lb.astype(jnp.int32)
        if ids.ndim == 2:
            ids = ids[:, 0]
        if pt is not None:
            nodes = pt.astype(jnp.int32)              # [N, L]
            bits = pc.astype(x.dtype)
            live = (nodes >= 0)
            nodes_safe = jnp.maximum(nodes, 0)
        else:
            c = ids + num_classes                      # [N]
            L = int(2 * num_classes - 1).bit_length() - 1
            js = jnp.arange(L)
            nodes = (c[:, None] >> (js[None, :] + 1)) - 1
            bits = ((c[:, None] >> js[None, :]) & 1).astype(x.dtype)
            # get_length = FindLastSet(c) - 1: bit j participates iff
            # j < floor(log2(c))
            length = (jnp.floor(jnp.log2(c.astype(jnp.float32)))
                      ).astype(jnp.int32)
            live = js[None, :] < length[:, None]
            nodes_safe = jnp.clip(nodes, 0, num_classes - 2)
        wsel = w[nodes_safe]                           # [N, L, D]
        pre = jnp.einsum("nd,nld->nl", x, wsel)
        if b is not None:
            pre = pre + b.reshape(-1)[nodes_safe]
        # sum over live bits of softplus(pre) - bit*pre  (= -log sigmoid
        # of the signed branch logit)
        term = jax.nn.softplus(pre) - bits * pre
        loss = jnp.sum(jnp.where(live, term, 0.0), axis=1, keepdims=True)
        return loss

    return run_op(f, [input, label, weight, *extra], "hsigmoid_loss")
