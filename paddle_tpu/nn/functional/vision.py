"""Spatial sampling functionals: grid_sample / affine_grid / temporal_shift.

Reference parity: `python/paddle/nn/functional/vision.py:122` (grid_sample
over the grid_sampler op), `affine_grid` (same file), `temporal_shift`
(`python/paddle/nn/functional/input.py` family / fluid temporal_shift op).

TPU design: the samplers are GATHER problems. Every (n, ho, wo) output
pixel's four corner taps become flat indices into the [C, H*W] image and
run as four `jnp.take` gathers batched over N via vmap — XLA lowers these
to efficient dynamic-gathers; there is no scalar loop and no data-dependent
shape anywhere, so the ops jit cleanly into larger programs (STN blocks,
deformable heads).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._dispatch import ensure_tensor, run_op


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * (size - 1) / 2.0
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect(coord, size, align_corners):
    """Triangle-wave reflection onto the valid range (grid_sampler
    reflect_coordinates contract: reflect around [0, size-1] when
    align_corners else [-0.5, size-0.5])."""
    if size == 1:
        return jnp.zeros_like(coord)
    if align_corners:
        lo, span = 0.0, float(size - 1)
    else:
        lo, span = -0.5, float(size)
    t = jnp.abs(coord - lo)
    extra = jnp.mod(t, span)
    flips = jnp.floor(t / span)
    even = jnp.mod(flips, 2.0) == 0
    return jnp.where(even, extra + lo, span - extra + lo)


def _gather_2d(img_flat, iy, ix, W):
    """img_flat [C, H*W]; iy/ix int32 [P] -> [C, P]."""
    return jnp.take(img_flat, iy * W + ix, axis=1)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """x [N,C,H,W], grid [N,Hg,Wg,2] with (x, y) in [-1, 1] -> [N,C,Hg,Wg]."""
    x, grid = ensure_tensor(x), ensure_tensor(grid)
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear|nearest, got {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode {padding_mode}")

    def f(xa, ga):
        N, C, H, W = xa.shape
        Hg, Wg = ga.shape[1], ga.shape[2]
        gx = _unnormalize(ga[..., 0].astype(jnp.float32), W, align_corners)
        gy = _unnormalize(ga[..., 1].astype(jnp.float32), H, align_corners)

        if padding_mode == "reflection":
            gx = _reflect(gx, W, align_corners)
            gy = _reflect(gy, H, align_corners)
        if padding_mode in ("border", "reflection"):
            gx = jnp.clip(gx, 0.0, W - 1)
            gy = jnp.clip(gy, 0.0, H - 1)

        def sample_one(img, fx, fy):
            """img [C,H,W]; fx/fy [P] -> [C,P]."""
            imgf = img.reshape(C, H * W)
            if mode == "nearest":
                ix = jnp.round(fx).astype(jnp.int32)
                iy = jnp.round(fy).astype(jnp.int32)
                valid = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
                v = _gather_2d(imgf, jnp.clip(iy, 0, H - 1),
                               jnp.clip(ix, 0, W - 1), W)
                if padding_mode == "zeros":
                    v = jnp.where(valid[None], v, 0.0)
                return v
            x0 = jnp.floor(fx)
            y0 = jnp.floor(fy)
            wx1 = (fx - x0).astype(img.dtype)
            wy1 = (fy - y0).astype(img.dtype)
            x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
            out = jnp.zeros((C, fx.shape[0]), img.dtype)
            for dy in (0, 1):
                for dx in (0, 1):
                    ix, iy = x0i + dx, y0i + dy
                    w = (wx1 if dx else 1 - wx1) * (wy1 if dy else 1 - wy1)
                    valid = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
                    v = _gather_2d(imgf, jnp.clip(iy, 0, H - 1),
                                   jnp.clip(ix, 0, W - 1), W)
                    if padding_mode == "zeros":
                        w = jnp.where(valid, w, 0.0)
                    out = out + v * w[None]
            return out

        out = jax.vmap(sample_one)(xa, gx.reshape(N, -1), gy.reshape(N, -1))
        return out.reshape(N, C, Hg, Wg)

    return run_op(f, [x, grid], "grid_sample")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N,2,3] -> sampling grid [N,H,W,2] for grid_sample."""
    theta = ensure_tensor(theta)
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy().reshape(-1)]  # tpu-lint: disable=host-sync (paddle API: Tensor out_shape -> static ints)
    N, _, H, W = [int(v) for v in out_shape]

    def f(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], -1).reshape(1, H * W, 3)  # [1,HW,3]
        # coordinate math must not round through the MXU's bf16 path
        grid = jnp.einsum("nhk,nck->nhc", jnp.broadcast_to(
            base, (th.shape[0], H * W, 3)).astype(th.dtype), th,
            precision=jax.lax.Precision.HIGHEST)
        return grid.reshape(th.shape[0], H, W, 2)

    return run_op(f, [theta], "affine_grid")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """TSM temporal shift (fluid `temporal_shift` op): [N*T, C, H, W] with
    the first shift_ratio*C channels shifted t-1 <- t, the next block
    t+1 <- t, rest unchanged; zero padding at the clip edges."""
    x = ensure_tensor(x)
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"bad data_format {data_format}")

    def f(xa):
        a = xa
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        NT, C, H, W = a.shape
        T = seg_num
        N = NT // T
        a = a.reshape(N, T, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        fwd = jnp.concatenate(
            [a[:, 1:, :c1], jnp.zeros_like(a[:, :1, :c1])], axis=1)
        bwd = jnp.concatenate(
            [jnp.zeros_like(a[:, :1, c1:c2]), a[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([fwd, bwd, a[:, :, c2:]], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return run_op(f, [x], "temporal_shift")
