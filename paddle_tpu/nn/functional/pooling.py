"""Pooling functionals via `lax.reduce_window`.

Reference parity: `python/paddle/nn/functional/pooling.py`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._dispatch import ensure_tensor, run_op


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(int(x) for x in v)


def _pool(x, nd, kernel, stride, padding, reducer, init, ceil_mode, exclusive=True,
          data_format="NCHW", count_include_pad=False):
    x = ensure_tensor(x)
    channel_last = not data_format.upper().startswith("NC")
    k = _tuplize(kernel, nd)
    s = _tuplize(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        p = _tuplize(padding, nd) if not (isinstance(padding, (list, tuple)) and
                                          isinstance(padding[0], (list, tuple))) else padding
        pads = [(int(pi), int(pi)) if isinstance(pi, (int, np.integer)) else
                (int(pi[0]), int(pi[1])) for pi in p]

    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        full_pads = [(0, 0)] + (pads or [(0, 0)] * nd) + [(0, 0)]
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        full_pads = [(0, 0), (0, 0)] + (pads or [(0, 0)] * nd)

    def f(a):
        if pad_mode == "SAME":
            pp = "SAME"
        elif pad_mode == "VALID":
            pp = "VALID"
        else:
            pp = full_pads
        if reducer == "max":
            return jax.lax.reduce_window(a, -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                                         else jnp.iinfo(a.dtype).min,
                                         jax.lax.max, window, strides, pp)
        # avg pool: sum then divide by count
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pp)
        if count_include_pad or pad_mode == "VALID" or (pads is None and pad_mode is None):
            return summed / np.prod(k)
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pp)
        return summed / counts

    return run_op(f, [x], f"{reducer}_pool{nd}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, 1, kernel_size, stride, padding, "max", None, ceil_mode,
                 data_format=data_format)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, 2, kernel_size, stride, padding, "max", None, ceil_mode,
                 data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, 3, kernel_size, stride, padding, "max", None, ceil_mode,
                 data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, 1, kernel_size, stride, padding, "avg", None, ceil_mode,
                 data_format=data_format, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, 2, kernel_size, stride, padding, "avg", None, ceil_mode,
                 data_format=data_format, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, 3, kernel_size, stride, padding, "avg", None, ceil_mode,
                 data_format=data_format, count_include_pad=not exclusive)


def _adaptive(x, nd, output_size, reducer, data_format):
    x = ensure_tensor(x)
    channel_last = not data_format.upper().startswith("NC")
    out = _tuplize(output_size, nd)
    spatial = tuple(x.shape[1:-1]) if channel_last else tuple(x.shape[2:])
    # exact adaptive pooling when divisible; general case via mean over index buckets
    if all(s % o == 0 for s, o in zip(spatial, out)):
        k = tuple(s // o for s, o in zip(spatial, out))
        return _pool(x, nd, k, k, 0, reducer, None, False, data_format=data_format)

    def f(a):
        arr = a
        axes = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
        for d, (size, o) in enumerate(zip(spatial, out)):
            ax = axes[d]
            starts = (np.arange(o) * size) // o
            ends = ((np.arange(o) + 1) * size + o - 1) // o
            pieces = []
            for s0, e0 in zip(starts, ends):
                seg = jax.lax.slice_in_dim(arr, int(s0), int(e0), axis=ax)
                red = jnp.max(seg, axis=ax, keepdims=True) if reducer == "max" else \
                    jnp.mean(seg, axis=ax, keepdims=True)
                pieces.append(red)
            arr = jnp.concatenate(pieces, axis=ax)
        return arr

    return run_op(f, [x], f"adaptive_{reducer}_pool{nd}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, 1, output_size, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, 2, output_size, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, 3, output_size, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 1, output_size, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 2, output_size, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 3, output_size, "max", "NCDHW")
