"""Pooling functionals via `lax.reduce_window`.

Reference parity: `python/paddle/nn/functional/pooling.py`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._dispatch import ensure_tensor, run_op


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(int(x) for x in v)


def _pool(x, nd, kernel, stride, padding, reducer, init, ceil_mode, exclusive=True,
          data_format="NCHW", count_include_pad=False):
    x = ensure_tensor(x)
    channel_last = not data_format.upper().startswith("NC")
    k = _tuplize(kernel, nd)
    s = _tuplize(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        p = _tuplize(padding, nd) if not (isinstance(padding, (list, tuple)) and
                                          isinstance(padding[0], (list, tuple))) else padding
        pads = [(int(pi), int(pi)) if isinstance(pi, (int, np.integer)) else
                (int(pi[0]), int(pi[1])) for pi in p]

    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        full_pads = [(0, 0)] + (pads or [(0, 0)] * nd) + [(0, 0)]
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        full_pads = [(0, 0), (0, 0)] + (pads or [(0, 0)] * nd)

    def f(a):
        if pad_mode == "SAME":
            pp = "SAME"
        elif pad_mode == "VALID":
            pp = "VALID"
        else:
            pp = full_pads
        if reducer == "max":
            return jax.lax.reduce_window(a, -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                                         else jnp.iinfo(a.dtype).min,
                                         jax.lax.max, window, strides, pp)
        # avg pool: sum then divide by count
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pp)
        if count_include_pad or pad_mode == "VALID" or (pads is None and pad_mode is None):
            return summed / np.prod(k)
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pp)
        return summed / counts

    return run_op(f, [x], f"{reducer}_pool{nd}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        return _max_pool_with_mask(x, 1, kernel_size, stride, padding,
                                   ceil_mode, data_format)
    return _pool(x, 1, kernel_size, stride, padding, "max", None, ceil_mode,
                 data_format=data_format)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, 2, kernel_size, stride, padding,
                                   ceil_mode, data_format)
    return _pool(x, 2, kernel_size, stride, padding, "max", None, ceil_mode,
                 data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, 3, kernel_size, stride, padding,
                                   ceil_mode, data_format)
    return _pool(x, 3, kernel_size, stride, padding, "max", None, ceil_mode,
                 data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, 1, kernel_size, stride, padding, "avg", None, ceil_mode,
                 data_format=data_format, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, 2, kernel_size, stride, padding, "avg", None, ceil_mode,
                 data_format=data_format, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, 3, kernel_size, stride, padding, "avg", None, ceil_mode,
                 data_format=data_format, count_include_pad=not exclusive)


def _adaptive(x, nd, output_size, reducer, data_format):
    x = ensure_tensor(x)
    channel_last = not data_format.upper().startswith("NC")
    out = _tuplize(output_size, nd)
    spatial = tuple(x.shape[1:-1]) if channel_last else tuple(x.shape[2:])
    # exact adaptive pooling when divisible; general case via mean over index buckets
    if all(s % o == 0 for s, o in zip(spatial, out)):
        k = tuple(s // o for s, o in zip(spatial, out))
        return _pool(x, nd, k, k, 0, reducer, None, False, data_format=data_format)

    def f(a):
        arr = a
        axes = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
        for d, (size, o) in enumerate(zip(spatial, out)):
            ax = axes[d]
            starts = (np.arange(o) * size) // o
            ends = ((np.arange(o) + 1) * size + o - 1) // o
            pieces = []
            for s0, e0 in zip(starts, ends):
                seg = jax.lax.slice_in_dim(arr, int(s0), int(e0), axis=ax)
                red = jnp.max(seg, axis=ax, keepdims=True) if reducer == "max" else \
                    jnp.mean(seg, axis=ax, keepdims=True)
                pieces.append(red)
            arr = jnp.concatenate(pieces, axis=ax)
        return arr

    return run_op(f, [x], f"adaptive_{reducer}_pool{nd}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, 1, output_size, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, 2, output_size, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, 3, output_size, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 1, output_size, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 2, output_size, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, 3, output_size, "max", "NCDHW")


def _max_pool_with_mask(x, nd, kernel, stride, padding, ceil_mode=False,
                        data_format="NCHW"):
    """Max pool that also returns the argmax mask (flat index into the
    input's spatial extent per channel — the max_pool_with_index op
    contract consumed by max_unpool). Implemented as a static loop over
    the kernel offsets: each offset is one strided slice of the padded
    input, stacked and argmaxed — no data-dependent shapes. NCHW-family
    layouts only (the reference's with-index op is NCHW-only too)."""
    import itertools
    if not data_format.upper().startswith("NC"):
        from ...core.enforce import InvalidArgumentError
        raise InvalidArgumentError(
            "max_pool with return_mask requires a channel-first layout "
            f"(got data_format={data_format!r}) — the reference "
            "max_poolNd_with_index op is NCHW-only too")
    x = ensure_tensor(x)
    k = _tuplize(kernel, nd)
    s = _tuplize(stride if stride is not None else kernel, nd)
    p = _tuplize(padding, nd)

    def f(a):
        spatial = a.shape[2:]
        if ceil_mode:
            out_sp = [-(-(spatial[i] + 2 * p[i] - k[i]) // s[i]) + 1
                      for i in range(nd)]
        else:
            out_sp = [(spatial[i] + 2 * p[i] - k[i]) // s[i] + 1
                      for i in range(nd)]
        neg = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) \
            else jnp.iinfo(a.dtype).min
        # right pad covers both the kernel overhang and any ceil_mode
        # partial window (whole-window -inf taps can never win the argmax)
        ap = jnp.pad(a, [(0, 0), (0, 0)]
                     + [(p[i], p[i] + k[i] + s[i]) for i in range(nd)],
                     constant_values=neg)
        vals, idxs = [], []
        for off in itertools.product(*[range(k[i]) for i in range(nd)]):
            sl = (slice(None), slice(None)) + tuple(
                slice(off[i], off[i] + s[i] * out_sp[i], s[i]) for i in range(nd))
            vals.append(ap[sl])
            # global flat index of this tap for every output position
            coord = [jnp.arange(out_sp[i]) * s[i] + off[i] - p[i]
                     for i in range(nd)]
            flat = 0
            for i in range(nd):
                shape1 = [1] * nd
                shape1[i] = out_sp[i]
                flat = flat * spatial[i] + coord[i].reshape(shape1)
            vals_shape = (1, 1) + tuple(out_sp)
            idxs.append(jnp.broadcast_to(flat.reshape(vals_shape),
                                         a.shape[:2] + tuple(out_sp)))
        vstack = jnp.stack(vals)                 # [K, N, C, *out]
        istack = jnp.stack(idxs)
        arg = jnp.argmax(vstack, axis=0)
        out = jnp.take_along_axis(vstack, arg[None], axis=0)[0]
        mask = jnp.take_along_axis(istack, arg[None], axis=0)[0]
        return out, mask.astype(jnp.int32)

    return run_op(f, [x], f"max_pool{nd}d_with_index")


def _max_unpool(x, indices, nd, kernel_size, stride, padding, output_size,
                data_format):
    """Scatter pooled values back to their argmax positions
    (`python/paddle/nn/layer/pooling.py:1215` MaxUnPool family /
    unpool op). Zeros elsewhere; duplicate indices follow scatter order."""
    x, indices = ensure_tensor(x), ensure_tensor(indices)
    k = _tuplize(kernel_size, nd)
    s = _tuplize(stride if stride is not None else kernel_size, nd)
    p = _tuplize(padding, nd)
    idx_v = indices._value

    def f(a):
        in_sp = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(v) for v in output_size)[-nd:]
        else:
            out_sp = tuple((in_sp[i] - 1) * s[i] - 2 * p[i] + k[i]
                           for i in range(nd))
        N, C = a.shape[0], a.shape[1]
        P = int(np.prod(in_sp))
        tot = int(np.prod(out_sp))
        af = a.reshape(N * C, P)
        idxf = idx_v.reshape(N * C, P).astype(jnp.int32)
        out = jnp.zeros((N * C, tot), a.dtype)
        out = out.at[jnp.arange(N * C)[:, None], idxf].set(af, mode="drop")
        return out.reshape((N, C) + out_sp)

    return run_op(f, [x], f"max_unpool{nd}d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format)
