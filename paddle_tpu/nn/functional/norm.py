"""Normalization functionals.

Reference parity: `python/paddle/nn/functional/norm.py` (batch_norm,
layer_norm, instance_norm, group_norm, local_response_norm). Running-stat
updates happen OUTSIDE the tape (buffers), matching fluid's in-place
mean/variance variables.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.autograd import no_grad
from ...ops._dispatch import ensure_tensor, run_op


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None,
               name=None):
    x = ensure_tensor(x)
    channel_last = not data_format.upper().startswith("NC")
    c_axis = x.ndim - 1 if channel_last else 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    use_batch = training and not use_global_stats
    if use_batch:
        with no_grad():
            # the tracer check gates the COMPUTATION, not just the buffer
            # write: under trace the update is discarded anyway, and
            # computing bm/bv first left 3 dead eqns per BN layer in every
            # traced training program (found by tpu-lint's dead-op rule)
            if running_mean is not None and not isinstance(
                    x._value, jax.core.Tracer):
                bm = jnp.mean(x._value, axis=reduce_axes)
                bv = jnp.var(x._value, axis=reduce_axes)
                running_mean._value = (momentum * running_mean._value
                                       + (1 - momentum) * bm.astype(running_mean._value.dtype))
                running_var._value = (momentum * running_var._value
                                      + (1 - momentum) * bv.astype(running_var._value.dtype))

        def f(a, *rest):
            m = jnp.mean(a, axis=reduce_axes, keepdims=True)
            v = jnp.var(a, axis=reduce_axes, keepdims=True)
            out = (a - m) * jax.lax.rsqrt(v + epsilon)
            return _affine(out, rest)
    else:
        rm = running_mean._value.reshape(bshape)
        rv = running_var._value.reshape(bshape)

        def f(a, *rest):
            out = (a - rm.astype(a.dtype)) * jax.lax.rsqrt(rv.astype(a.dtype) + epsilon)
            return _affine(out, rest)

    def _affine(out, rest):
        if len(rest) == 2:
            w, b = rest
            return out * w.reshape(bshape) + b.reshape(bshape)
        if len(rest) == 1:
            return out * rest[0].reshape(bshape)
        return out

    ins = [x]
    if weight is not None:
        ins.append(ensure_tensor(weight))
    if bias is not None:
        ins.append(ensure_tensor(bias))
    return run_op(f, ins, "batch_norm")


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(normalized_shape)
    axes = tuple(range(x.ndim - nd, x.ndim))

    def f(a, *rest):
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        if len(rest) == 2:
            return out * rest[0] + rest[1]
        if len(rest) == 1:
            return out * rest[0]
        return out

    ins = [x]
    if weight is not None:
        ins.append(ensure_tensor(weight))
    if bias is not None:
        ins.append(ensure_tensor(bias))
    return run_op(f, ins, "layer_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW",
                  name=None):
    x = ensure_tensor(x)
    channel_last = not data_format.upper().startswith("NC")
    c_axis = x.ndim - 1 if channel_last else 1
    spatial = tuple(i for i in range(2, x.ndim)) if not channel_last else \
        tuple(i for i in range(1, x.ndim - 1))
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    def f(a, *rest):
        m = jnp.mean(a, axis=spatial, keepdims=True)
        v = jnp.var(a, axis=spatial, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        if len(rest) == 2:
            return out * rest[0].reshape(bshape) + rest[1].reshape(bshape)
        if len(rest) == 1:
            return out * rest[0].reshape(bshape)
        return out

    ins = [x]
    if weight is not None:
        ins.append(ensure_tensor(weight))
    if bias is not None:
        ins.append(ensure_tensor(bias))
    return run_op(f, ins, "instance_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = not data_format.upper().startswith("NC")
    c_axis = x.ndim - 1 if channel_last else 1
    c = x.shape[c_axis]
    bshape = [1] * x.ndim
    bshape[c_axis] = c

    def f(a, *rest):
        if channel_last:
            perm = [0, a.ndim - 1] + list(range(1, a.ndim - 1))
            a_t = jnp.transpose(a, perm)
        else:
            a_t = a
        n = a_t.shape[0]
        grouped = a_t.reshape((n, num_groups, c // num_groups) + a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        m = jnp.mean(grouped, axis=axes, keepdims=True)
        v = jnp.var(grouped, axis=axes, keepdims=True)
        out = ((grouped - m) * jax.lax.rsqrt(v + epsilon)).reshape(a_t.shape)
        if channel_last:
            inv = [0] + list(range(2, a.ndim)) + [1]
            out = jnp.transpose(out, inv)
        if len(rest) == 2:
            return out * rest[0].reshape(bshape) + rest[1].reshape(bshape)
        if len(rest) == 1:
            return out * rest[0].reshape(bshape)
        return out

    ins = [x]
    if weight is not None:
        ins.append(ensure_tensor(weight))
    if bias is not None:
        ins.append(ensure_tensor(bias))
    return run_op(f, ins, "group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                        name=None):
    x = ensure_tensor(x)

    def f(a):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        padded = jnp.pad(sq, [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2))
        window = sum(padded[:, i:i + c] for i in range(size))
        return a / jnp.power(k + alpha * window / size, beta)

    return run_op(f, [x], "local_response_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (TPU-era addition; used by modern LLM blocks)."""
    x = ensure_tensor(x)

    def f(a, *rest):
        ms = jnp.mean(jnp.square(a), axis=-1, keepdims=True)
        out = a * jax.lax.rsqrt(ms + epsilon)
        return out * rest[0] if rest else out

    ins = [x] + ([ensure_tensor(weight)] if weight is not None else [])
    return run_op(f, ins, "rms_norm")
