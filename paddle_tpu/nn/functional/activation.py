"""Activation functionals.

Reference parity: `python/paddle/nn/functional/activation.py`. All lower to
single fused XLA elementwise graphs (fused into neighbouring matmuls on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._dispatch import ensure_tensor, run_op, unary_op

relu = unary_op(jax.nn.relu, "relu")
relu6 = unary_op(jax.nn.relu6, "relu6")
sigmoid = unary_op(jax.nn.sigmoid, "sigmoid")
tanh = unary_op(jnp.tanh, "tanh")
silu = unary_op(jax.nn.silu, "silu")
swish = silu
mish = unary_op(lambda a: a * jnp.tanh(jax.nn.softplus(a)), "mish")
hardswish = unary_op(jax.nn.hard_swish, "hardswish")
hardsigmoid = unary_op(lambda a: jnp.clip(a / 6.0 + 0.5, 0.0, 1.0), "hardsigmoid")
tanhshrink = unary_op(lambda a: a - jnp.tanh(a), "tanhshrink")
softsign = unary_op(jax.nn.soft_sign, "softsign")
log_sigmoid = unary_op(jax.nn.log_sigmoid, "log_sigmoid")


def gelu(x, approximate=False, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jax.nn.gelu(a, approximate=approximate), [x], "gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jax.nn.leaky_relu(a, negative_slope), [x], "leaky_relu")


def elu(x, alpha=1.0, name=None):
    return run_op(lambda a: jax.nn.elu(a, alpha), [ensure_tensor(x)], "elu")


def celu(x, alpha=1.0, name=None):
    return run_op(lambda a: jax.nn.celu(a, alpha), [ensure_tensor(x)], "celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return run_op(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                  [ensure_tensor(x)], "selu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return run_op(lambda a: jnp.clip(a, min, max), [ensure_tensor(x)], "hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return run_op(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0),
                  [ensure_tensor(x)], "hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return run_op(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        [ensure_tensor(x)], "softshrink")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return run_op(
        lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta),
        [ensure_tensor(x)], "softplus")


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def f(a, w):
        if w.size > 1:
            ax = 1 if data_format.upper().startswith("NC") else a.ndim - 1
            shape = [1] * a.ndim
            shape[ax] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, a * w)

    return run_op(f, [x, weight], "prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...core import random as rnd
    x = ensure_tensor(x)
    if training:
        k = rnd.next_key()
        slope = jax.random.uniform(k, tuple(x.shape), dtype=jnp.float32,
                                   minval=lower, maxval=upper)
        return run_op(lambda a: jnp.where(a >= 0, a, a * slope.astype(a.dtype)), [x], "rrelu")
    mid = (lower + upper) / 2.0
    return run_op(lambda a: jnp.where(a >= 0, a, a * mid), [x], "rrelu")


def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jax.nn.softmax(a, axis=axis), [x], "softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jax.nn.log_softmax(a, axis=axis), [x], "log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as rnd
    x = ensure_tensor(x)
    g = -jnp.log(-jnp.log(
        jax.random.uniform(rnd.next_key(), tuple(x.shape), minval=1e-20, maxval=1.0)))

    def f(a):
        y = jax.nn.softmax((a + g.astype(a.dtype)) / temperature, axis=axis)
        if hard:
            y_hard = jax.nn.one_hot(jnp.argmax(y, axis=axis), a.shape[axis], axis=axis,
                                    dtype=a.dtype)
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return run_op(f, [x], "gumbel_softmax")


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def f(a):
        shp = list(a.shape)
        c = shp[axis]
        shp[axis:axis + 1] = [c // groups, groups]
        return jnp.max(a.reshape(shp), axis=axis + 1)

    return run_op(f, [x], "maxout")


def glu(x, axis=-1, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jax.nn.glu(a, axis=axis), [x], "glu")


def thresholded_relu(x, threshold=1.0, name=None):
    """x if x > threshold else 0 (`nn/functional/activation.py`
    thresholded_relu)."""
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.where(a > threshold, a, 0.0).astype(a.dtype),
                  [x], "thresholded_relu")


def _inplace_variant(x, out):
    """paddle's `op_` inplace contract on immutable XLA buffers: the result
    rebinds the INPUT tensor's storage (so existing holders observe the
    update) and autograd continues through the returned tensor's tape node
    — identical numerics, one extra buffer during the op."""
    x._value = out._value
    x.stop_gradient = out.stop_gradient
    return out


def relu_(x, name=None):
    x = ensure_tensor(x)
    return _inplace_variant(x, relu(x))


def elu_(x, alpha=1.0, name=None):
    x = ensure_tensor(x)
    return _inplace_variant(x, elu(x, alpha))


def softmax_(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    return _inplace_variant(x, softmax(x, axis=axis, dtype=dtype))


def tanh_(x, name=None):
    x = ensure_tensor(x)
    return _inplace_variant(x, tanh(x))
