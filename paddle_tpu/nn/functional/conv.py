"""Convolution functionals over `lax.conv_general_dilated`.

Reference parity: `python/paddle/nn/functional/conv.py` (conv1d/2d/3d,
conv*_transpose) with paddle's NCHW default + OIHW weights. TPU-first: we
pass explicit dimension numbers and let XLA pick the internal layout; the
MXU sees one fused conv per call (vs cuDNN algo selection in the reference).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._dispatch import ensure_tensor, run_op
from ...ops.math import _precision


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _padding_arg(padding, n, strides=None):
    """paddle padding: int, list[int], list[pair], 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _dims(nd, channel_last):
    if nd == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd, data_format):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    channel_last = data_format.upper().endswith("C") and not data_format.upper().startswith("NC")
    strides = _tuplize(stride, nd)
    dilations = _tuplize(dilation, nd)
    pad = _padding_arg(padding, nd)
    dn = _dims(nd, channel_last)

    def f(a, w, *rest):
        from ...amp.state import maybe_cast
        a, w = maybe_cast(a, w)
        rest = tuple(maybe_cast(r) for r in rest)
        if channel_last:
            # weights stay OIHW (paddle layout); lax wants HWIO for NHWC
            perm = list(range(2, 2 + nd)) + [1, 0]
            w = jnp.transpose(w, perm)
        out = jax.lax.conv_general_dilated(
            a, w, strides, pad, rhs_dilation=dilations,
            dimension_numbers=dn, feature_group_count=groups,
            precision=_precision())
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[out.ndim - 1 if channel_last else 1] = b.size
            out = out + b.reshape(shape)
        return out

    ins = [x, weight] + ([ensure_tensor(bias)] if bias is not None else [])
    return run_op(f, ins, f"conv{nd}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, nd, data_format, output_size=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    channel_last = data_format.upper().endswith("C") and not data_format.upper().startswith("NC")
    strides = _tuplize(stride, nd)
    dilations = _tuplize(dilation, nd)
    opad = _tuplize(output_padding, nd) if output_padding is not None else (0,) * nd
    dn = _dims(nd, channel_last)

    if isinstance(padding, str):
        pads = padding.upper()
    else:
        pads = _padding_arg(padding, nd)

    def f(a, w, *rest):
        # paddle transpose-conv weight layout: [in, out/groups, *k] (IOHW)
        k = w.shape[2:]
        if isinstance(pads, str):
            lax_pad = pads
        else:
            # grad-of-conv padding: (k-1)*d - p  on each side, + output_padding on high side
            lax_pad = [((k[i] - 1) * dilations[i] - pads[i][0],
                        (k[i] - 1) * dilations[i] - pads[i][1] + opad[i])
                       for i in range(nd)]
        if groups > 1:
            ws = jnp.split(w, groups, axis=0)
            xs = jnp.split(a, groups, axis=-1 if channel_last else 1)
            outs = []
            for wi, xi in zip(ws, xs):
                outs.append(_one(xi, wi, lax_pad))
            return jnp.concatenate(outs, axis=-1 if channel_last else 1) if not rest else \
                _add_bias(jnp.concatenate(outs, axis=-1 if channel_last else 1), rest[0])
        out = _one(a, w, lax_pad)
        if rest:
            out = _add_bias(out, rest[0])
        return out

    def _one(a, w, lax_pad):
        # flip spatial dims and swap I/O to express transpose conv as dilated conv
        wf = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        wf = jnp.swapaxes(wf, 0, 1)  # [out, in, *k] -> OIHW
        if channel_last:
            perm = list(range(2, 2 + nd)) + [1, 0]
            wf = jnp.transpose(wf, perm)
        return jax.lax.conv_general_dilated(
            a, wf, (1,) * nd, lax_pad, lhs_dilation=strides, rhs_dilation=dilations,
            dimension_numbers=dn, precision=_precision())

    def _add_bias(out, b):
        shape = [1] * out.ndim
        shape[out.ndim - 1 if channel_last else 1] = b.size
        return out + b.reshape(shape)

    ins = [x, weight] + ([ensure_tensor(bias)] if bias is not None else [])
    return run_op(f, ins, f"conv{nd}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 1, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 3, data_format, output_size)
