"""Common functionals: linear, dropout, embedding, one_hot, interpolate, …

Reference parity: `python/paddle/nn/functional/common.py` + `input.py`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import random as rnd
from ...core.tensor import Tensor
from ...ops._dispatch import ensure_tensor, run_op
from ...ops.math import _precision


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W shaped [in, out] (paddle convention)."""
    from ...amp.state import maybe_cast
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    if bias is not None:
        bias = ensure_tensor(bias)

        def f(a, w, b):
            a, w, b = maybe_cast(a, w, b)
            return jnp.matmul(a, w, precision=_precision()) + b

        return run_op(f, [x, weight, bias], "linear")

    def f2(a, w):
        a, w = maybe_cast(a, w)
        return jnp.matmul(a, w, precision=_precision())

    return run_op(f2, [x, weight], "linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return run_op(lambda a: a * (1.0 - p), [x], "dropout_infer")
        return x
    key = rnd.next_key()
    shape = tuple(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = tuple(s if i in axes else 1 for i, s in enumerate(shape))

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        out = jnp.where(keep, a, jnp.zeros((), a.dtype))
        if mode == "upscale_in_train":
            out = out / jnp.asarray(1.0 - p, a.dtype)
        return out

    return run_op(f, [x], "dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = rnd.next_key()

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(a.shape))
        q = 1.0 - p
        coef_a = (q + alpha_p ** 2 * q * p) ** -0.5
        coef_b = -coef_a * alpha_p * p
        return coef_a * jnp.where(keep, a, jnp.asarray(alpha_p, a.dtype)) + coef_b

    return run_op(f, [x], "alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Lookup rows of weight [vocab, dim] by integer ids.

    sparse=True: the weight gradient is a SelectedRows (rows=looked-up ids,
    values=row cotangents) instead of a dense [vocab, dim] scatter —
    reference lookup_table grad -> SelectedRows -> sparse optimizer path."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    from ...ops import lazy as _lazy
    if _lazy._ACTIVE:
        # ids ride the op as a closure (a host-side value), so a deferred
        # payload (e.g. position ids computed by a lazy add) must resolve
        # here — this is a sync point either way
        _lazy._materialize_inputs([x])
    ids = x._value.astype(jnp.int32)

    def f(w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out

    if not sparse:
        return run_op(f, [weight], "embedding")

    from ...core import autograd
    from ...core.selected_rows import SelectedRows
    from ...core.tensor import Tensor
    height, dim = weight._value.shape
    out = Tensor(f(weight._value))
    if autograd.is_grad_enabled() and not weight.stop_gradient:
        flat_ids = ids.reshape(-1)
        pad = padding_idx

        def vjp(g):
            g = g._value if hasattr(g, "_value") else g
            vals = jnp.reshape(g, (-1, dim))
            if pad is not None and pad >= 0:
                vals = jnp.where((flat_ids == pad)[:, None],
                                 jnp.zeros((), vals.dtype), vals)
            return (SelectedRows(flat_ids, vals, height),)

        autograd.record_node(vjp, [weight], [out], "lookup_table_sparse_grad")
    return out


def one_hot(x, num_classes, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.nn.one_hot(x._value.astype(jnp.int32), num_classes, dtype=jnp.float32))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)
    k = label.shape[-1]

    def f(a):
        if prior_dist is not None:
            pd = ensure_tensor(prior_dist)._value
            return (1 - epsilon) * a + epsilon * pd
        return (1 - epsilon) * a + epsilon / k

    return run_op(f, [label], "label_smooth")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    cf = data_format.upper().startswith("NC")
    spatial = x.shape[2:] if cf else x.shape[1:-1]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()  # tpu-lint: disable=host-sync (paddle API: Tensor size -> static ints)
        out_spatial = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
            [scale_factor] * len(spatial)
        out_spatial = [int(s * f) for s, f in zip(spatial, sf)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def f(a):
        if cf:
            full = list(a.shape[:2]) + out_spatial
        else:
            full = [a.shape[0]] + out_spatial + [a.shape[-1]]
        return jax.image.resize(a, full, method=jmode)

    return run_op(f, [x], "interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return run_op(f, [x], "pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = downscale_factor

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = a.transpose(0, 1, 3, 5, 2, 4)
        return a.reshape(n, c * r * r, h // r, w // r)

    return run_op(f, [x], "pixel_unshuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col: [N,C,H,W] -> [N, C*kh*kw, L]."""
    x = ensure_tensor(x)
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    if isinstance(paddings, int):
        pt = pb = pl = pr = paddings
    elif len(paddings) == 2:
        pt = pb = paddings[0]
        pl = pr = paddings[1]
    else:
        pt, pl, pb, pr = paddings

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
        oh = (a.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
        ow = (a.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            a, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * kh * kw, oh * ow)

    return run_op(f, [x], "unfold")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)

    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)

    return run_op(f, [x1, x2], "cosine_similarity")


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)

    def f(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    ins = [x1, x2, weight] + ([ensure_tensor(bias)] if bias is not None else [])
    return run_op(f, ins, "bilinear")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)

    def f(a):
        n = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True), 1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return run_op(f, [x], "normalize")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...ops.manipulation import pad as _pad
    return _pad(x, pad, mode, value, data_format)


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (arXiv:2010.05222).

    Reference parity: `python/paddle/nn/functional/common.py:1636`
    (class_center_sample over the `class_center_sample` op). Keeps every
    positive class center in `label`, pads with uniformly sampled negative
    centers up to `num_samples` (keeps all positives if there are more),
    and remaps labels to indices into the sampled-center list.

    TPU design: the sample runs HOST-SIDE on the [N] label vector (numpy) —
    its output length is data-dependent (|positives| can exceed
    num_samples), which has no stable jit shape, and the op runs once per
    step on a tiny tensor; the downstream sharded matmul + 
    margin_cross_entropy are the device work. Randomness draws from the
    framework generator (core/random), so paddle.seed reproduces the
    reference's seeded behavior. Multi-rank (PartialFC over mp): each rank
    calls with its LOCAL num_classes; `rank_offset` positions follow the
    reference's cumulative remap (labels map into the concatenation of all
    ranks' sampled lists) via the parallel env when `group` is not None.
    """
    import numpy as _np
    from ...core.tensor import Tensor as _T
    from ...core import random as _rnd

    lab = _np.asarray(label._value if isinstance(label, _T) else label)
    lab = lab.reshape(-1).astype(_np.int64)
    if num_samples > num_classes:
        from ...core.enforce import InvalidArgumentError
        raise InvalidArgumentError(
            f"Expected num_samples <= {num_classes}, got {num_samples}")

    rank, nranks = 0, 1
    if group is not None:
        from ...parallel import env as _penv
        rank = getattr(group, "rank", None)
        if rank is None:
            rank = _penv.get_rank() if hasattr(_penv, "get_rank") else 0
        nranks = getattr(group, "nranks", 1) or 1

    # One base seed drawn once, then every rank deterministically computes
    # EVERY rank's sampled list (seed derived per rank). All ranks see the
    # same (all-gathered) labels and the same generator state under
    # paddle.seed, so the lists — and therefore the cumulative remap —
    # agree everywhere without a count exchange. (Positions must account
    # for each rank's negatives too: a negative can sort before a
    # positive, so positions inside the FULL sampled list are required.)
    import jax as _jax
    seed_arr = _np.asarray(
        _jax.random.key_data(_rnd.default_generator().next_key()))
    base_seed = int(seed_arr.reshape(-1)[-1]) % (2 ** 31)

    def _rank_sample(r):
        rlo = r * num_classes
        pos = _np.unique(lab[(lab >= rlo) & (lab < rlo + num_classes)]) - rlo
        n_neg = max(0, num_samples - len(pos))
        if n_neg == 0:
            return pos
        rng = _np.random.RandomState((base_seed + r) % (2 ** 31))
        negatives = _np.setdiff1d(_np.arange(num_classes, dtype=_np.int64),
                                  pos, assume_unique=True)
        picked = rng.choice(negatives, size=n_neg, replace=False)
        return _np.sort(_np.concatenate([pos, picked]))

    all_sampled = [_rank_sample(r) for r in range(nranks)]
    offsets = _np.cumsum([0] + [len(s) for s in all_sampled])
    sampled = all_sampled[rank]

    remapped = _np.zeros_like(lab)
    for r in range(nranks):
        rlo = r * num_classes
        sel = (lab >= rlo) & (lab < rlo + num_classes)
        if sel.any():
            remapped[sel] = offsets[r] + _np.searchsorted(
                all_sampled[r], lab[sel] - rlo)
    # sampled centers are LOCAL indices in [0, num_classes) — PartialFC
    # gathers them from this rank's local weight shard (reference
    # common.py:1636 multi-GPU example output)
    return _T(remapped), _T(sampled)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths [*, 1?] -> [..., maxlen] 0/1 mask
    (`fluid/layers/sequence_lod.py:1324`)."""
    import jax.numpy as jnp
    from ...ops._dispatch import ensure_tensor as _et, nondiff_op
    x = _et(x)
    ml = maxlen if maxlen is not None else int(np.max(np.asarray(x._value)))

    def f(a):
        rng = jnp.arange(ml)
        return (rng < a[..., None]).astype(dtype)

    return nondiff_op(f, [x])


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Embed the last dim as (offset) diagonals of new matrices
    (`nn/functional/extension.py` diag_embed)."""
    import jax.numpy as jnp
    from ...ops._dispatch import ensure_tensor as _et, run_op
    x = _et(input)

    def f(a):
        n = a.shape[-1] + abs(offset)
        out_ndim = a.ndim + 1
        d1 = dim1 % out_ndim
        d2 = dim2 % out_ndim
        m = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        m = m.at[..., r, c].set(a)
        # m currently has the matrix at the LAST two axes; move to (d1, d2)
        perm = list(range(out_ndim - 2))
        # insert axis positions
        order = []
        src = 0
        for i in range(out_ndim):
            if i == d1:
                order.append(out_ndim - 2)
            elif i == d2:
                order.append(out_ndim - 1)
            else:
                order.append(perm[src])
                src += 1
        return jnp.transpose(m, order)

    return run_op(f, [x], "diag_embed")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad H/W (`nn/functional/common.py` zeropad2d): padding
    [left, right, top, bottom]."""
    import jax.numpy as jnp
    from ...ops._dispatch import ensure_tensor as _et, run_op
    x = _et(x)
    l, r, t, b = [int(v) for v in (padding.numpy() if hasattr(padding, "numpy")  # tpu-lint: disable=host-sync (paddle API: Tensor padding -> static ints)
                                   else padding)]

    def f(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (t, b), (l, r)))
        return jnp.pad(a, ((0, 0), (t, b), (l, r), (0, 0)))

    return run_op(f, [x], "zeropad2d")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im: inverse of unfold (`nn/functional/common.py:1803`). x
    [N, C*kh*kw, L] -> [N, C, H, W] by scatter-adding the patch columns
    back — implemented as ONE scatter-add over precomputed static index
    maps (no scalar loops; XLA lowers to an efficient scatter on TPU)."""
    import jax.numpy as jnp
    from ...ops._dispatch import ensure_tensor as _et, run_op
    x = _et(x)
    to2 = lambda v: [v, v] if isinstance(v, int) else list(v)  # noqa: E731
    oh, ow = to2(output_sizes)
    kh, kw = to2(kernel_sizes)
    sh, sw = to2(strides)
    ph, pw = to2(paddings)
    dh, dw = to2(dilations)
    lh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    lw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    def f(a):
        N, ckk, L = a.shape
        C = ckk // (kh * kw)
        if L != lh * lw:
            from ...core.enforce import InvalidArgumentError
            raise InvalidArgumentError(
                f"fold: L={L} does not match computed {lh}*{lw}")
        a = a.reshape(N, C, kh, kw, lh, lw)
        # target row/col for each (ki, li) pair, with padding offset
        ky = np.arange(kh) * dh
        kx = np.arange(kw) * dw
        ly = np.arange(lh) * sh
        lx = np.arange(lw) * sw
        rows = ky[:, None] + ly[None, :] - ph        # [kh, lh]
        cols = kx[:, None] + lx[None, :] - pw        # [kw, lw]
        out = jnp.zeros((N, C, oh + 2 * max(ph, 0) + kh * dh,
                         ow + 2 * max(pw, 0) + kw * dw), a.dtype)
        # scatter into a padded canvas with shifted coords, then crop —
        # keeps every index in-bounds without per-element masks
        out = out.at[:, :, rows[:, None, :, None] + ph,
                     cols[None, :, None, :] + pw].add(
            jnp.transpose(a, (0, 1, 2, 3, 4, 5)))
        return out[:, :, ph:ph + oh, pw:pw + ow]

    return run_op(f, [x], "fold")


def gather_tree(ids, parents):
    """Beam-search backtrace (`fluid/layers/nn.py:15226` gather_tree):
    ids/parents [T, B, beam] -> full predicted sequences per beam, walking
    parent pointers backwards from the last step (one lax.scan, reversed)."""
    import jax
    import jax.numpy as jnp
    from ...ops._dispatch import ensure_tensor as _et, nondiff_op
    ids_t, par_t = _et(ids), _et(parents)

    def f(a, p):
        T, B, K = a.shape
        binc = jnp.arange(B)[:, None]

        def step(beam_sel, xs):
            ids_row, par_row = xs          # [B, K]
            out = ids_row[binc, beam_sel]
            nxt = par_row[binc, beam_sel]
            return nxt, out

        init = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
        _, outs = jax.lax.scan(step, init, (a[::-1], p[::-1]))
        return outs[::-1]

    return nondiff_op(lambda a, p: f(a, p), [ids_t, par_t])


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention over a CSR pattern
    (`nn/functional/sparse_attention.py:23`). q/k/v [B, H, S, D]; offsets
    [B, H, S+1]; columns [B, H, nnz].

    TPU note: the CSR pattern becomes a dense boolean mask and the softmax
    runs masked — on TPU the MXU prefers the dense masked form at these
    sizes (the reference's CUDA kernel exists to exploit CSR on SIMT);
    long-sequence sparse patterns should use the flash/ring kernels
    instead, which skip masked BLOCKS structurally."""
    import jax
    import jax.numpy as jnp
    from ...ops._dispatch import ensure_tensor as _et, run_op
    import math as _math
    q, k, v = _et(query), _et(key), _et(value)
    off = np.asarray(_et(sparse_csr_offset)._value)
    col = np.asarray(_et(sparse_csr_columns)._value)

    def f(qa, ka, va, *rest):
        B, H, S, D = qa.shape
        # vectorized CSR -> dense mask: one scatter over all nnz entries
        mask = np.zeros((B, H, S, S), bool)
        counts = np.diff(off, axis=-1)                 # [B, H, S]
        rows = np.repeat(np.tile(np.arange(S), B * H), counts.reshape(-1))
        bh = np.repeat(np.arange(B * H), counts.sum(-1).reshape(-1))
        mask.reshape(B * H, S, S)[bh, rows, col.reshape(-1)] = True
        m = jnp.asarray(mask)
        s = jnp.einsum("bhsd,bhtd->bhst", qa, ka,
                       preferred_element_type=jnp.float32) / _math.sqrt(D)
        i = 0
        if key_padding_mask is not None:
            kpm = rest[i]; i += 1
            m = m & (kpm[:, None, None, :] > 0)
        if attn_mask is not None:
            am = rest[i]; i += 1
            m = m & (am[None, None] > 0) if am.ndim == 2 else m & (am > 0)
        s = jnp.where(m, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(m.any(-1, keepdims=True), p, 0.0)
        return jnp.einsum("bhst,bhtd->bhsd", p.astype(va.dtype), va,
                          preferred_element_type=jnp.float32).astype(qa.dtype)

    extra = [_et(key_padding_mask)] if key_padding_mask is not None else []
    extra += [_et(attn_mask)] if attn_mask is not None else []
    return run_op(f, [q, k, v, *extra], "sparse_attention")
