"""Attention functionals.

Reference parity: the reference's `fused_attention_op.cu` /
`operators/fused/fmha_ref.h` (unfused-softmax FMHA). TPU-first: a single
jitted softmax(QK^T)V graph that XLA fuses; on TPU hardware the Pallas
flash-attention kernel (paddle_tpu.kernels.flash_attention) is used for
long sequences.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...ops._dispatch import ensure_tensor, run_op
from ...ops.math import _precision


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """query/key/value: [batch, seqlen, num_heads, head_dim] (paddle layout).

    Uses the Pallas flash-attention kernel on TPU for seq_len >= 1024 with no
    custom mask; otherwise the fused XLA reference path.
    """
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    mask_arr = ensure_tensor(attn_mask)._value if attn_mask is not None else None

    seq_len = q.shape[1]
    head_dim = q.shape[-1]
    use_flash = False
    # measured crossover on v5e (fwd+bwd): with bf16 inputs the native-dtype
    # MXU dots win from 1k up (2.2x at 1k, 2.7x at 2k, 5.7x at 8k); fp32
    # inputs keep the old 4k crossover (fp32 MXU dots were only at parity
    # there). The Pallas kernel also keeps memory O(S).
    # threshold keyed on the PROMOTED dtype: bf16 q against an fp32 KV
    # cache runs fp32 dots inside the kernel (operands are promoted at the
    # flash boundary), where the old 4k crossover still applies
    _promoted = jnp.result_type(q._value.dtype, k._value.dtype, v._value.dtype)
    _flash_min_seq = 1024 if _promoted == jnp.bfloat16 else 4096
    if mask_arr is None and dropout_p == 0.0 and seq_len >= _flash_min_seq \
            and k.shape[1] == seq_len and v.shape[1] == seq_len \
            and head_dim in (64, 128, 256):
        try:
            import jax as _j
            use_flash = any(d.platform == "tpu" for d in _j.devices())
        except Exception:
            use_flash = False
    if use_flash:
        from ...kernels.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=is_causal)

    scale = 1.0 / math.sqrt(head_dim)
    # key drawn OUTSIDE the traced fn: drawing inside would leak a tracer
    # into the global RNG state under the eager dispatch cache
    drop_key = None
    if dropout_p > 0.0 and training:
        from ...core import random as rnd
        drop_key = rnd.next_key()

    def f(qa, ka, va):
        # [B,S,H,D] -> [B,H,S,D]
        qa = jnp.swapaxes(qa, 1, 2)
        ka = jnp.swapaxes(ka, 1, 2)
        va = jnp.swapaxes(va, 1, 2)
        logits = jnp.einsum("bhsd,bhtd->bhst", qa, ka, precision=_precision()) * scale
        if is_causal:
            s, t = logits.shape[-2], logits.shape[-1]
            cmask = jnp.tril(jnp.ones((s, t), dtype=bool))
            logits = jnp.where(cmask, logits, jnp.asarray(-1e9, logits.dtype))
        if mask_arr is not None:
            m = mask_arr
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, jnp.asarray(-1e9, logits.dtype))
            else:
                logits = logits + m.astype(logits.dtype)
        probs = jax.nn.softmax(logits, axis=-1)
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
        out = jnp.einsum("bhst,bhtd->bhsd", probs, va, precision=_precision())
        return jnp.swapaxes(out, 1, 2)

    return run_op(f, [q, k, v], "scaled_dot_product_attention")
