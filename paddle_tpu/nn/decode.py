"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference parity: `python/paddle/fluid/layers/rnn.py` — Decoder (:790),
BeamSearchDecoder (:866, _beam_search_step/gather_tree semantics),
dynamic_decode (:1583, dygraph loop at :1340). Exposed as
`paddle.nn.BeamSearchDecoder` / `paddle.nn.dynamic_decode` like the
reference's 2.x surface.

TPU-first notes: each decode step is a handful of fused device ops
(cell step + log_softmax + masked top-k + beam gathers) driven by an eager
host loop with a device-side `finished` reduction as the stop predicate —
the reference's dygraph path, with the per-step math batched as
[batch*beam, ...] so the MXU sees one matmul per step regardless of beam
width. The backtrace (`gather_tree`) runs on host at finalize.
"""
from __future__ import annotations

from collections import namedtuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode", "gather_tree"]

_BeamState = namedtuple("_BeamState",
                        ["cell_states", "log_probs", "finished", "lengths"])
_BeamOutput = namedtuple("_BeamOutput", ["scores", "predicted_ids",
                                         "parent_ids"])


class Decoder:
    """Abstract decode contract (reference Decoder, rnn.py:790)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


def gather_tree(step_ids, parent_ids):
    """Backtrace beam parents: [T, batch, beam] ids + parents -> the full
    sequences per surviving beam (reference nn.gather_tree op)."""
    ids = np.asarray(step_ids)
    parents = np.asarray(parent_ids)
    T, B, W = ids.shape
    out = np.zeros_like(ids)
    for b in range(B):
        for w in range(W):
            parent = w
            for t in range(T - 1, -1, -1):
                out[t, b, w] = ids[t, b, parent]
                parent = int(parents[t, b, parent])
    return out


class BeamSearchDecoder(Decoder):
    """Beam-search decoding over an RNN cell (reference rnn.py:866).

    The cell's inputs/states ride as [batch * beam_size, ...]; any other
    per-batch tensor used inside the cell (e.g. attention memory) must be
    tiled with `tile_beam_merge_with_batch` first.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # ---- beam/batch layout helpers ----
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch * beam_size, ...] (repeat per beam)."""
        x = ensure_tensor(x)
        return run_op(lambda a: jnp.repeat(a, beam_size, axis=0), [x],
                      "tile_beam")

    def _map_states(self, states, fn):
        if isinstance(states, (tuple, list)):
            return tuple(self._map_states(s, fn) for s in states)
        return fn(ensure_tensor(states))

    # ---- Decoder interface ----
    def initialize(self, initial_cell_states):
        states = self._map_states(
            initial_cell_states,
            lambda s: self.tile_beam_merge_with_batch(s, self.beam_size))
        first = initial_cell_states
        while isinstance(first, (tuple, list)):
            first = first[0]
        batch = ensure_tensor(first).shape[0]
        W = self.beam_size
        # only beam 0 is live initially, or every beam would decode the
        # same argmax path (reference kInfinite init)
        log_probs = np.full((batch, W), -1e9, np.float32)
        log_probs[:, 0] = 0.0
        start = np.full((batch * W,), self.start_token, np.int64)
        ids = Tensor(jnp.asarray(start))
        inputs = self.embedding_fn(ids) if self.embedding_fn else ids
        state = _BeamState(cell_states=states,
                           log_probs=jnp.asarray(log_probs),
                           finished=np.zeros((batch, W), bool),
                           lengths=np.zeros((batch, W), np.int64))
        return inputs, state, state.finished.copy()

    def step(self, time, inputs, states: _BeamState, **kwargs):
        W = self.beam_size
        cell_out, next_cell = self.cell(inputs, states.cell_states, **kwargs)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        logits = ensure_tensor(logits)
        V = logits.shape[-1]
        finished = states.finished                       # host [batch, W]
        fin_j = jnp.asarray(finished)
        log_probs_prev = states.log_probs               # [batch, W]

        def score_fn(lg):
            lp = jax.nn.log_softmax(lg, axis=-1).reshape(-1, W, V)
            # finished beams emit ONLY end_token at probability 1, so their
            # score is carried unchanged (reference noend_mask_tensor)
            mask = jnp.full((V,), -1e9, lp.dtype).at[self.end_token].set(0.0)
            lp = jnp.where(fin_j[:, :, None], mask[None, None, :], lp)
            return log_probs_prev[:, :, None] + lp      # [batch, W, V]

        scores = score_fn(logits._value)                 # device
        flat = scores.reshape(scores.shape[0], W * V)
        top_scores, top_idx = jax.lax.top_k(flat, W)

        # host copies for bookkeeping/backtrace (int64 on the numpy side:
        # device int64 truncates to int32 without jax_enable_x64)
        idx_np = np.asarray(top_idx).astype(np.int64)
        beam_np = idx_np // V
        tok_np = idx_np % V
        fin_gathered = np.take_along_axis(finished, beam_np, axis=1)
        len_gathered = np.take_along_axis(states.lengths, beam_np, axis=1)
        next_finished = fin_gathered | (tok_np == self.end_token)
        next_lengths = len_gathered + (~fin_gathered).astype(np.int64)

        # gather cell states along the beam axis
        batch = beam_np.shape[0]
        flat_sel = (np.arange(batch)[:, None] * W + beam_np).reshape(-1)
        sel = jnp.asarray(flat_sel)

        sel_t = Tensor(sel)

        def gather_state(s):
            # index rides as a positional input (an array-valued closure
            # would defeat the eager dispatch cache — see nn/layer/rnn.py)
            return run_op(lambda a, i: a[i], [s, sel_t], "gather_beam")

        next_cell = self._map_states(next_cell, gather_state)

        next_ids = Tensor(jnp.asarray(tok_np.reshape(-1)))
        next_inputs = self.embedding_fn(next_ids) if self.embedding_fn \
            else next_ids
        out = _BeamOutput(scores=np.asarray(top_scores),
                          predicted_ids=tok_np, parent_ids=beam_np)
        next_state = _BeamState(cell_states=next_cell,
                                log_probs=top_scores,
                                finished=next_finished,
                                lengths=next_lengths)
        return out, next_state, next_inputs, next_finished

    def finalize(self, outputs, final_states, sequence_lengths):
        step_ids = np.stack([o.predicted_ids for o in outputs])   # [T,B,W]
        parents = np.stack([o.parent_ids for o in outputs])
        predicted = gather_tree(step_ids, parents)
        return Tensor(jnp.asarray(predicted)), final_states

    @property
    def tracks_own_finished(self):
        return True


def _default_stack(outputs):
    """Stack per-step outputs time-major when the decoder has no finalize:
    arrays stack to [T, ...]; namedtuple outputs stack per field."""
    first = outputs[0]
    if hasattr(first, "_fields"):  # namedtuple of arrays
        return type(first)(*(Tensor(jnp.stack(
            [jnp.asarray(getattr(o, f)) for o in outputs]))
            for f in first._fields))
    return Tensor(jnp.stack([jnp.asarray(
        o._value if isinstance(o, Tensor) else o) for o in outputs]))


def _time_to_batch_major(x):
    if isinstance(x, Tensor) or hasattr(x, "shape"):
        return run_op(lambda a: jnp.moveaxis(a, 0, 1), [ensure_tensor(x)],
                      "transpose")
    if hasattr(x, "_fields"):
        return type(x)(*(_time_to_batch_major(v) for v in x))
    return x


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Step `decoder` until every sequence finishes or `max_step_num`.

    Returns (outputs, final_states) — plus sequence_lengths when
    `return_length` (reference dynamic_decode, rnn.py:1583). `is_test` is
    accepted for API parity (eager decode keeps no training state)."""
    if impute_finished and not decoder.tracks_own_finished:
        raise NotImplementedError(
            "impute_finished=True needs finished-state rectification for "
            "this decoder; implement tracks_own_finished (as "
            "BeamSearchDecoder does) or decode without imputation")
    inputs, states, finished = decoder.initialize(inits)
    seq_lengths = np.zeros(np.shape(finished), np.int64)
    outputs = []
    step = 0
    if max_step_num is None:
        # a model that never emits end_token must not hang the host loop
        # forever (ADVICE r4): apply a large default cap, warn on hit
        max_step_num = 10000
        _warn_on_cap = True
    else:
        _warn_on_cap = False
    while not bool(np.all(finished)):
        out, states, inputs, finished = decoder.step(step, inputs, states,
                                                     **kwargs)
        if not decoder.tracks_own_finished:
            seq_lengths += ~np.asarray(finished)
        outputs.append(out)
        step += 1
        if max_step_num is not None and step >= max_step_num:
            if _warn_on_cap:
                import warnings
                warnings.warn(
                    "dynamic_decode hit the default 10000-step cap without "
                    "every beam emitting end_token; pass max_step_num to "
                    "raise or silence this")
            break
    lengths = getattr(states, "lengths", seq_lengths)
    try:
        final_outputs, final_states = decoder.finalize(outputs, states,
                                                       lengths)
    except NotImplementedError:
        final_outputs, final_states = _default_stack(outputs), states
    if not output_time_major:
        final_outputs = _time_to_batch_major(final_outputs)
    if return_length:
        return final_outputs, final_states, Tensor(jnp.asarray(lengths))
    return final_outputs, final_states

