"""Weight initializers.

Reference parity: `python/paddle/nn/initializer/` (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign) over the fluid `initializer.py` fan-in/fan-out conventions.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.tensor import Tensor


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weights are OIHW: fan_in = in_ch * kh*kw, fan_out = out_ch * kh*kw
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, param, block=None):
        arr = self._generate(tuple(param.shape), param._value.dtype)
        param._value = arr
        return param

    def _generate(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        return jax.random.normal(rnd.next_key(), shape, dtype=jnp.float32).astype(dtype) \
            * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        z = jax.random.truncated_normal(rnd.next_key(), -2.0, 2.0, shape, dtype=jnp.float32)
        return (z * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, shape, dtype):
        return jax.random.uniform(rnd.next_key(), shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(rnd.next_key(), shape, dtype=jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rnd.next_key(), shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def _generate(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return (jax.random.normal(rnd.next_key(), shape, dtype=jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def _generate(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rnd.next_key(), shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def _generate(self, shape, dtype):
        arr = self.value.numpy() if isinstance(self.value, Tensor) else np.asarray(self.value)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return jnp.asarray(arr, dtype=dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def _generate(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc, ic)
        centers = [s // 2 for s in shape[2:]]
        for i in range(mins):
            out[(i, i) + tuple(centers)] = 1.0
        return jnp.asarray(out, dtype=dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def _generate(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        a = jax.random.normal(rnd.next_key(), (max(rows, cols), min(rows, cols)),
                              dtype=jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols].reshape(shape)).astype(dtype)
