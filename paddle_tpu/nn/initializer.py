"""Weight initializers.

Reference parity: `python/paddle/nn/initializer/` (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign) over the fluid `initializer.py` fan-in/fan-out conventions.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.tensor import Tensor


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weights are OIHW: fan_in = in_ch * kh*kw, fan_out = out_ch * kh*kw
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, param, block=None):
        arr = self._generate(tuple(param.shape), param._value.dtype)
        param._value = arr
        return param

    def _generate(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        return jax.random.normal(rnd.next_key(), shape, dtype=jnp.float32).astype(dtype) \
            * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        z = jax.random.truncated_normal(rnd.next_key(), -2.0, 2.0, shape, dtype=jnp.float32)
        return (z * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, shape, dtype):
        return jax.random.uniform(rnd.next_key(), shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(rnd.next_key(), shape, dtype=jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rnd.next_key(), shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def _generate(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return (jax.random.normal(rnd.next_key(), shape, dtype=jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def _generate(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rnd.next_key(), shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def _generate(self, shape, dtype):
        arr = self.value.numpy() if isinstance(self.value, Tensor) else np.asarray(self.value)  # tpu-lint: disable=host-sync (host-side param init)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return jnp.asarray(arr, dtype=dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def _generate(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc, ic)
        centers = [s // 2 for s in shape[2:]]
        for i in range(mins):
            out[(i, i) + tuple(centers)] = 1.0
        return jnp.asarray(out, dtype=dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def _generate(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        a = jax.random.normal(rnd.next_key(), (max(rows, cols), min(rows, cols)),
                              dtype=jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols].reshape(shape)).astype(dtype)


class Bilinear(Initializer):
    """Bilinear-upsample kernel init for transposed convs
    (`nn/initializer/Bilinear`): weight [C_out, C_in, kH, kW] gets the
    classic bilinear interpolation stencil per channel."""

    def __call__(self, shape, dtype=jnp.float32):
        import numpy as _np
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D conv weight")
        co, ci, kh, kw = [int(v) for v in shape]
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        c_h = (kh - 1) / 2.0 if kh % 2 == 1 else f_h - 0.5
        c_w = (kw - 1) / 2.0 if kw % 2 == 1 else f_w - 0.5
        og = _np.ogrid[:kh, :kw]
        filt = (1 - abs(og[0] - c_h) / f_h) * (1 - abs(og[1] - c_w) / f_w)
        w = _np.zeros((co, ci, kh, kw), _np.float32)
        w[range(min(co, ci)), range(min(co, ci))] = filt
        if co != ci:
            w[:, :] = filt          # broadcast stencil when shapes differ
        return jnp.asarray(w, dtype)


def calculate_gain(nonlinearity, param=None):
    """Reference `nn/initializer/calculate_gain` table."""
    import math as _m
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
             "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0,
             "relu": _m.sqrt(2.0), "selu": 3.0 / 4.0}
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return _m.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity {nonlinearity!r}")
    return gains[nonlinearity]


_GLOBAL_INITIALIZER = [None, None]   # (weight_init, bias_init)


def set_global_initializer(weight_init, bias_init=None):
    """Default initializers applied by Layer.create_parameter when the
    caller passes none (reference set_global_initializer)."""
    _GLOBAL_INITIALIZER[0] = weight_init
    _GLOBAL_INITIALIZER[1] = bias_init
