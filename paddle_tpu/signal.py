"""paddle.signal: frame / overlap_add / stft / istft.

Reference parity: `python/paddle/signal.py` (frame :32, overlap_add :153,
stft :236, istft :390). TPU-first: framing is a gather over precomputed
window indices and the DFT rides `jnp.fft` (XLA-lowered), so an stft is
two fused device ops instead of the reference's frame_op + fft_c2r CUDA
kernels; istft's overlap-add is one scatter-add.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor
from .ops._dispatch import ensure_tensor, run_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_idx(seq_len, frame_length, hop_length):
    n_frames = 1 + (seq_len - frame_length) // hop_length
    return (np.arange(frame_length)[:, None]
            + hop_length * np.arange(n_frames)[None, :])   # [L, T]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames: [..., seq] -> [..., L, T] (axis=-1)
    or [seq, ...] -> [T, L, ...] (axis=0)."""
    x = ensure_tensor(x)
    if axis not in (0, -1):
        raise ValueError(f"frame: axis must be 0 or -1, got {axis}")
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, but got {hop_length}")
    seq = x.shape[-1] if axis == -1 else x.shape[0]
    if not 0 < frame_length <= seq:
        raise ValueError(
            f"frame_length should be in (0, {seq}], got {frame_length}")
    idx = _frame_idx(seq, frame_length, hop_length)

    if axis == -1:
        return run_op(lambda a: a[..., idx], [x], "frame")
    return run_op(lambda a: a[idx.T], [x], "frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: [..., L, T] -> [..., seq] (axis=-1) or
    [T, L, ...] -> [seq, ...] (axis=0); overlaps sum."""
    x = ensure_tensor(x)
    if axis not in (0, -1):
        raise ValueError(f"overlap_add: axis must be 0 or -1, got {axis}")
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, but got {hop_length}")
    if axis == -1:
        L, T = x.shape[-2], x.shape[-1]
    else:
        T, L = x.shape[0], x.shape[1]
    seq = (T - 1) * hop_length + L
    idx = _frame_idx(seq, L, hop_length)  # [L, T]

    def f(a):
        if axis == -1:
            out = jnp.zeros(tuple(a.shape[:-2]) + (seq,), a.dtype)
            return out.at[..., idx].add(a)
        out = jnp.zeros((seq,) + tuple(a.shape[2:]), a.dtype)
        return out.at[idx.T].add(a)

    return run_op(f, [x], "overlap_add")


def _resolve_window(window, win_length, n_fft, dtype):
    if win_length > n_fft:
        raise ValueError(
            f"win_length ({win_length}) should not be greater than n_fft "
            f"({n_fft})")
    if window is None:
        w = jnp.ones((win_length,), dtype)
    else:
        w = window._value if isinstance(window, Tensor) else jnp.asarray(window)
        if w.shape[0] != win_length:
            raise ValueError(
                f"window length {w.shape[0]} != win_length {win_length}")
    if win_length < n_fft:  # center-pad to n_fft
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
    return w


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """STFT of a [..., seq] real or complex signal -> complex
    [..., n_fft//2 + 1, T] (onesided) / [..., n_fft, T]."""
    x = ensure_tensor(x)
    if x.ndim not in (1, 2):
        raise ValueError(f"stft: x must be 1D or 2D, got rank {x.ndim}")
    hop_length = hop_length if hop_length is not None else n_fft // 4
    win_length = win_length if win_length is not None else n_fft
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, but got {hop_length}")
    is_complex = jnp.issubdtype(x._value.dtype, jnp.complexfloating)
    if is_complex and onesided:
        raise ValueError("stft: onesided is not supported for complex input")
    wdt = jnp.float64 if x._value.dtype in (jnp.float64, jnp.complex128) \
        else jnp.float32
    w = _resolve_window(window, win_length, n_fft, wdt)

    def f(a):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, ((0, 0), (pad, pad)),
                        mode={"reflect": "reflect", "constant": "constant",
                              "replicate": "edge"}.get(pad_mode, pad_mode))
        idx = _frame_idx(a.shape[-1], n_fft, hop_length)      # [N, T]
        frames = a[..., idx] * w[None, :, None].astype(a.dtype)  # [B, N, T]
        if onesided:
            spec = jnp.fft.rfft(frames, n=n_fft, axis=-2)
        else:
            spec = jnp.fft.fft(frames, n=n_fft, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, wdt))
        return spec[0] if squeeze else spec

    return run_op(f, [x], "stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Least-squares inverse STFT of [..., n_bins, T] -> [..., seq]."""
    x = ensure_tensor(x)
    if x.ndim not in (2, 3):
        raise ValueError(f"istft: x must be 2D or 3D, got rank {x.ndim}")
    hop_length = hop_length if hop_length is not None else n_fft // 4
    win_length = win_length if win_length is not None else n_fft
    if onesided and return_complex:
        raise ValueError(
            "istft: return_complex=True requires onesided=False (a onesided "
            "spectrum reconstructs a real signal)")
    n_bins = x.shape[-2]
    want = n_fft // 2 + 1 if onesided else n_fft
    if n_bins != want:
        raise ValueError(
            f"istft: expected {want} frequency bins, got {n_bins}")
    wdt = jnp.float64 if x._value.dtype == jnp.complex128 else jnp.float32
    w = _resolve_window(window, win_length, n_fft, wdt)

    def f(a):
        squeeze = a.ndim == 2
        if squeeze:
            a = a[None]
        if normalized:
            a = a * jnp.sqrt(jnp.asarray(n_fft, wdt))
        if onesided:
            fr = jnp.fft.irfft(a, n=n_fft, axis=-2)            # [B, N, T]
        else:
            fr = jnp.fft.ifft(a, n=n_fft, axis=-2)
            if not return_complex:
                fr = fr.real
        fr = fr * w[None, :, None].astype(fr.dtype)
        T = fr.shape[-1]
        seq = (T - 1) * hop_length + n_fft
        idx = _frame_idx(seq, n_fft, hop_length)
        out = jnp.zeros(fr.shape[:-2] + (seq,), fr.dtype).at[..., idx].add(fr)
        # NOLA normalization: divide by the summed squared window
        wsq = (w.astype(wdt) ** 2)[:, None] * jnp.ones((1, T), wdt)
        den = jnp.zeros((seq,), wdt).at[idx].add(wsq)
        out = out / jnp.maximum(den, 1e-11).astype(out.dtype)
        if center:
            out = out[..., n_fft // 2: seq - n_fft // 2]
        if length is not None:
            if out.shape[-1] < length:   # samples past the last full frame
                out = jnp.pad(out, [(0, 0)] * (out.ndim - 1)
                              + [(0, length - out.shape[-1])])
            else:
                out = out[..., :length]
        return out[0] if squeeze else out

    return run_op(f, [x], "istft")
