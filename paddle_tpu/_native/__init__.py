"""Native (C++) runtime components, loaded via ctypes.

Built from `csrc/` on first import (g++ -O2 -shared), cached under
`_native/build/`. Components:
  - TCPStore server/client (rendezvous KV; reference tcp_store.cc parity)
  - stats monitor (platform/monitor.cc STAT_ADD parity)
  - threadpool batch assembler + aligned host buffers (buffered_reader /
    DataLoader-worker hot loop)
  - C inference API client (predict_capi.cpp) and AES-128-CTR model
    crypto (crypto.cpp) — these two are native-ONLY (no python fallback;
    framework.crypto raises a clear error without a toolchain)

The store/monitor/assembler components have pure-python fallbacks, so the
core package works even where the toolchain is unavailable; `available()`
reports which path is active.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.normpath(os.path.join(_HERE, "..", "..", "csrc"))
_BUILD = os.path.join(_HERE, "build")
_LIB_PATH = os.path.join(_BUILD, "libpaddle_tpu_native.so")

_lib = None
_lock = threading.Lock()


def _sources():
    return [os.path.join(_CSRC, f)
            for f in ("tcpstore.cpp", "runtime.cpp", "predict_capi.cpp",
                      "crypto.cpp", "ps_server.cpp")]


def _src_hash() -> str:
    import hashlib
    h = hashlib.sha256()
    for s in _sources():
        if os.path.exists(s):
            with open(s, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


_HASH_PATH = os.path.join(_BUILD, "libpaddle_tpu_native.srchash")


def _needs_build() -> bool:
    # The build dir is never committed (gitignored): the .so always comes
    # from compiling csrc/ on this machine. A recorded source hash — not
    # mtimes, which checkout resets — decides staleness.
    if not os.path.exists(_LIB_PATH) or not os.path.exists(_HASH_PATH):
        return True
    with open(_HASH_PATH) as f:
        return f.read().strip() != _src_hash()


def _build() -> bool:
    try:
        os.makedirs(_BUILD, exist_ok=True)
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
               "-o", _LIB_PATH] + _sources()
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            import warnings
            warnings.warn(f"native build failed, using python fallback:\n{r.stderr[:500]}")
            return False
        with open(_HASH_PATH, "w") as f:
            f.write(_src_hash())
        return True
    except Exception:
        return False


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build() and not _build():
            _lib = False
            return _lib
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _lib = False
            return _lib
        # signatures
        lib.tcpstore_server_start.restype = ctypes.c_void_p
        lib.tcpstore_server_start.argtypes = [ctypes.c_int,
                                              ctypes.POINTER(ctypes.c_int)]
        lib.tcpstore_server_stop.argtypes = [ctypes.c_void_p]
        lib.tcpstore_client_connect.restype = ctypes.c_void_p
        lib.tcpstore_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                                ctypes.c_int]
        lib.tcpstore_client_free.argtypes = [ctypes.c_void_p]
        lib.tcpstore_set.restype = ctypes.c_int
        lib.tcpstore_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_char_p, ctypes.c_uint32]
        lib.tcpstore_get.restype = ctypes.c_int64
        lib.tcpstore_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_char_p, ctypes.c_uint32]
        lib.tcpstore_add.restype = ctypes.c_int64
        lib.tcpstore_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        lib.tcpstore_wait.restype = ctypes.c_int
        lib.tcpstore_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.monitor_add.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.monitor_get.restype = ctypes.c_int64
        lib.monitor_get.argtypes = [ctypes.c_char_p]
        lib.monitor_reset.argtypes = [ctypes.c_char_p]
        lib.monitor_dump.restype = ctypes.c_int64
        lib.monitor_dump.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.batch_assemble.restype = ctypes.c_int
        lib.batch_assemble.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_void_p),
                                       ctypes.c_int64, ctypes.c_int64]
        lib.ps_native_server_start.restype = ctypes.c_void_p
        lib.ps_native_server_start.argtypes = [ctypes.c_int,
                                               ctypes.POINTER(ctypes.c_int)]
        lib.ps_native_server_stop.argtypes = [ctypes.c_void_p]
        lib.ps_native_server_port.restype = ctypes.c_int
        lib.ps_native_server_port.argtypes = [ctypes.c_void_p]
        lib.ps_native_add_sparse.restype = ctypes.c_int
        lib.ps_native_add_sparse.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
            ctypes.c_float, ctypes.c_float, ctypes.c_longlong]
        lib.ps_native_add_dense.restype = ctypes.c_int
        lib.ps_native_add_dense.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
            ctypes.c_float, ctypes.c_longlong, ctypes.c_longlong]
        lib.ps_native_add_sparse_v2.restype = ctypes.c_int
        lib.ps_native_add_sparse_v2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
            ctypes.c_float, ctypes.c_float, ctypes.c_longlong,
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float]
        lib.ps_native_add_dense_v2.restype = ctypes.c_int
        lib.ps_native_add_dense_v2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
            ctypes.c_float, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float]
        _lib = lib
        return _lib


def available() -> bool:
    return bool(_load())


# ---------------- TCPStore ----------------
class TCPStore:
    """paddle.distributed.TCPStore parity (is_master spawns the server)."""

    def __init__(self, host="127.0.0.1", port=0, is_master=False, world_size=1,
                 timeout=30):
        import threading
        # one client socket per store object: requests/responses must pair
        # up, so concurrent use from heartbeat/watcher threads serializes
        self._io_lock = threading.Lock()
        lib = _load()
        self._lib = lib if lib else None
        self._server = None
        self._py = None
        self.host = host
        if self._lib:
            if is_master:
                out_port = ctypes.c_int(0)
                self._server = self._lib.tcpstore_server_start(port,
                                                               ctypes.byref(out_port))
                if not self._server:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                port = out_port.value
            self.port = port
            self._client = self._lib.tcpstore_client_connect(
                host.encode(), port, int(timeout * 1000))
            if not self._client:
                raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")
        else:  # pure-python fallback (single-process only)
            self._py = {}
            self.port = port

    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._py is not None:
            self._py[key] = data
            return
        with self._io_lock:
            rc = self._lib.tcpstore_set(self._client, key.encode(), data,
                                        len(data))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str) -> bytes:
        if self._py is not None:
            return self._py[key]
        buf = ctypes.create_string_buffer(1 << 20)
        with self._io_lock:
            n = self._lib.tcpstore_get(self._client, key.encode(), buf, 1 << 20)
        if n == -1:
            raise KeyError(key)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return buf.raw[:n]

    def add(self, key: str, amount: int) -> int:
        if self._py is not None:
            self._py[key] = str(int(self._py.get(key, b"0")) + amount).encode()
            return int(self._py[key])
        with self._io_lock:
            v = self._lib.tcpstore_add(self._client, key.encode(), amount)
        if v == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return v

    def wait(self, keys) -> None:
        keys = [keys] if isinstance(keys, str) else keys
        if self._py is not None:
            return
        for k in keys:
            with self._io_lock:
                rc = self._lib.tcpstore_wait(self._client, k.encode())
            if rc != 0:
                raise RuntimeError("TCPStore.wait failed")

    def __del__(self):
        try:
            if self._lib and getattr(self, "_client", None):
                self._lib.tcpstore_client_free(self._client)
            if self._lib and self._server:
                self._lib.tcpstore_server_stop(self._server)
        except Exception:
            pass


# ---------------- monitor ----------------
def stat_add(name: str, delta: int = 1):
    lib = _load()
    if lib:
        lib.monitor_add(name.encode(), delta)
    else:
        _PY_STATS[name] = _PY_STATS.get(name, 0) + delta


def stat_get(name: str) -> int:
    lib = _load()
    if lib:
        return lib.monitor_get(name.encode())
    return _PY_STATS.get(name, 0)


def stat_reset(name: str = ""):
    lib = _load()
    if lib:
        lib.monitor_reset(name.encode())
    elif name:
        _PY_STATS.pop(name, None)
    else:
        _PY_STATS.clear()


def stat_dump() -> dict:
    lib = _load()
    if lib:
        buf = ctypes.create_string_buffer(1 << 20)
        n = lib.monitor_dump(buf, 1 << 20)
        out = {}
        for line in buf.raw[:n].decode().splitlines():
            if "=" in line:
                k, v = line.rsplit("=", 1)
                out[k] = int(v)
        return out
    return dict(_PY_STATS)


_PY_STATS: dict = {}


# ---------------- batch assembler ----------------
def batch_assemble(dst, samples) -> bool:
    """Parallel-copy uniform numpy samples into the preallocated dst array.
    Returns False (caller should fall back) when native is unavailable or
    layouts are not contiguous."""
    import numpy as np
    lib = _load()
    if not lib:
        return False
    if not dst.flags["C_CONTIGUOUS"]:
        return False
    n = len(samples)
    sample_bytes = samples[0].nbytes
    ptrs = (ctypes.c_void_p * n)()
    for i, s in enumerate(samples):
        if not (isinstance(s, np.ndarray) and s.flags["C_CONTIGUOUS"]
                and s.nbytes == sample_bytes):
            return False
        ptrs[i] = s.ctypes.data
    rc = lib.batch_assemble(dst.ctypes.data, ptrs, n, sample_bytes)
    return rc == 0
