"""paddle.onnx parity surface.

Reference parity: `python/paddle/onnx/export.py` (paddle2onnx bridge).
This build's portable deployment artifact is StableHLO (`jit.save` ->
`inference.Config` -> Predictor, plus the C ABI in csrc/predict_capi.cpp);
ONNX is an NVIDIA/CPU-runtime interchange format whose operator set the
XLA pipeline does not round-trip through. `export` here produces the
StableHLO artifact at the requested path and records the reasoning in the
raised guidance when a true .onnx file is demanded.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export `layer` for deployment. Writes the StableHLO artifact (the
    TPU-portable equivalent of the reference's paddle2onnx flow). If the
    caller explicitly requires ONNX bytes (path endswith '.onnx'), raise
    with guidance instead of silently writing a different format."""
    if str(path).endswith(".onnx"):
        raise NotImplementedError(
            "paddle.onnx.export: this TPU build deploys via StableHLO "
            "(jit.save -> inference.Predictor / C API), not ONNX — the "
            "XLA pipeline has no faithful ONNX opset round-trip. Export "
            "without the .onnx suffix to produce the StableHLO artifact, "
            "or run the reference paddle2onnx flow on a CPU/GPU build.")
    from ..jit.save_load import save
    save(layer, str(path), input_spec=input_spec, **configs)
    return str(path)
