"""paddle.text parity: tiny synthetic text datasets (zero-egress image)."""
import numpy as np

from ..io.dataset import Dataset


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 1024 if mode == "train" else 256
        self.docs = [rng.randint(1, 5000, (rng.randint(20, 100),)).astype("int64")
                     for _ in range(n)]
        self.labels = rng.randint(0, 2, (n,)).astype("int64")
        self.word_idx = {f"w{i}": i for i in range(5000)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        self.x = rng.rand(n, 13).astype("float32")
        w = rng.rand(13).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.rand(n)).astype("float32")[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)
