"""paddle.text parity: tiny synthetic text datasets (zero-egress image)."""
import numpy as np

from ..io.dataset import Dataset


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 1024 if mode == "train" else 256
        self.docs = [rng.randint(1, 5000, (rng.randint(20, 100),)).astype("int64")
                     for _ in range(n)]
        self.labels = rng.randint(0, 2, (n,)).astype("int64")
        self.word_idx = {f"w{i}": i for i in range(5000)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        self.x = rng.rand(n, 13).astype("float32")
        w = rng.rand(13).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.rand(n)).astype("float32")[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Imikolov(Dataset):
    """imikolov ngram LM dataset surface (reference text/datasets/imikolov.py);
    synthetic ngrams over a Zipf-ish vocab (zero-egress image)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        n = 2000 if mode == "train" else 200
        rng = np.random.default_rng(0 if mode == "train" else 1)
        vocab = 1000
        probs = 1.0 / np.arange(1, vocab + 1)
        probs /= probs.sum()
        self.window_size = window_size
        self._grams = rng.choice(vocab, size=(n, window_size), p=probs)

    def __getitem__(self, idx):
        g = self._grams[idx]
        return tuple(g[:-1]) + (g[-1],)

    def __len__(self):
        return len(self._grams)


class Movielens(Dataset):
    """movielens rating surface (reference text/datasets/movielens.py):
    (user_id, gender, age, job, movie_id, categories, title, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        # disjoint train/test streams (no leakage between splits)
        rng = np.random.default_rng(rand_seed + (0 if mode == "train" else 1))
        n = 1800 if mode == "train" else 200
        self._rows = [(
            int(rng.integers(1, 500)),        # user id
            int(rng.integers(0, 2)),          # gender
            int(rng.integers(1, 7)),          # age bucket
            int(rng.integers(0, 21)),         # job
            int(rng.integers(1, 800)),        # movie id
            rng.integers(0, 18, 3).tolist(),  # category ids
            rng.integers(0, 5000, 4).tolist(),  # title word ids
            float(rng.integers(1, 6)),        # rating
        ) for _ in range(n)]

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)


class Conll05st(Dataset):
    """conll05 SRL surface (reference text/datasets/conll05.py, 9-column
    layout): word ids, 5 predicate-context windows (ctx_n2..ctx_p2),
    predicate ids, mark, label ids (synthetic)."""

    def __init__(self, data_file=None, word_dict_file=None, mode="train",
                 download=True):
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 500 if mode == "train" else 50
        self._rows = []
        for _ in range(n):
            ln = int(rng.integers(5, 30))
            words = rng.integers(0, 5000, ln)
            pred = int(rng.integers(0, ln))
            mark = np.zeros(ln, np.int64)
            mark[pred] = 1
            labels = rng.integers(0, 67, ln)
            # predicate context windows: words at pred-2 .. pred+2,
            # clamped at the sentence edges, broadcast over the sequence
            ctx = [np.full(ln, words[min(max(pred + off, 0), ln - 1)])
                   for off in (-2, -1, 0, 1, 2)]
            self._rows.append((words, *ctx, np.full(ln, words[pred]),
                               mark, labels))

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)
