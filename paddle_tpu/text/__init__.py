"""paddle.text parity: tiny synthetic text datasets (zero-egress image)."""
import numpy as np

from ..io.dataset import Dataset


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 1024 if mode == "train" else 256
        self.docs = [rng.randint(1, 5000, (rng.randint(20, 100),)).astype("int64")
                     for _ in range(n)]
        self.labels = rng.randint(0, 2, (n,)).astype("int64")
        self.word_idx = {f"w{i}": i for i in range(5000)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        self.x = rng.rand(n, 13).astype("float32")
        w = rng.rand(13).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.rand(n)).astype("float32")[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Imikolov(Dataset):
    """imikolov ngram LM dataset surface (reference text/datasets/imikolov.py);
    synthetic ngrams over a Zipf-ish vocab (zero-egress image)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        n = 2000 if mode == "train" else 200
        rng = np.random.default_rng(0 if mode == "train" else 1)
        vocab = 1000
        probs = 1.0 / np.arange(1, vocab + 1)
        probs /= probs.sum()
        self.window_size = window_size
        self._grams = rng.choice(vocab, size=(n, window_size), p=probs)

    def __getitem__(self, idx):
        g = self._grams[idx]
        return tuple(g[:-1]) + (g[-1],)

    def __len__(self):
        return len(self._grams)


class Movielens(Dataset):
    """movielens rating surface (reference text/datasets/movielens.py):
    (user_id, gender, age, job, movie_id, categories, title, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        # disjoint train/test streams (no leakage between splits)
        rng = np.random.default_rng(rand_seed + (0 if mode == "train" else 1))
        n = 1800 if mode == "train" else 200
        self._rows = [(
            int(rng.integers(1, 500)),        # user id
            int(rng.integers(0, 2)),          # gender
            int(rng.integers(1, 7)),          # age bucket
            int(rng.integers(0, 21)),         # job
            int(rng.integers(1, 800)),        # movie id
            rng.integers(0, 18, 3).tolist(),  # category ids
            rng.integers(0, 5000, 4).tolist(),  # title word ids
            float(rng.integers(1, 6)),        # rating
        ) for _ in range(n)]

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)


class Conll05st(Dataset):
    """conll05 SRL surface (reference text/datasets/conll05.py, 9-column
    layout): word ids, 5 predicate-context windows (ctx_n2..ctx_p2),
    predicate ids, mark, label ids (synthetic)."""

    def __init__(self, data_file=None, word_dict_file=None, mode="train",
                 download=True):
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 500 if mode == "train" else 50
        self._rows = []
        for _ in range(n):
            ln = int(rng.integers(5, 30))
            words = rng.integers(0, 5000, ln)
            pred = int(rng.integers(0, ln))
            mark = np.zeros(ln, np.int64)
            mark[pred] = 1
            labels = rng.integers(0, 67, ln)
            # predicate context windows: words at pred-2 .. pred+2,
            # clamped at the sentence edges, broadcast over the sequence
            ctx = [np.full(ln, words[min(max(pred + off, 0), ln - 1)])
                   for off in (-2, -1, 0, 1, 2)]
            self._rows.append((words, *ctx, np.full(ln, words[pred]),
                               mark, labels))

    def __getitem__(self, idx):
        return self._rows[idx]

    def __len__(self):
        return len(self._rows)


class WMT14(Dataset):
    """WMT14 en-fr pairs (`text/datasets/wmt14.py`). Synthetic token pairs
    in the same ((src, trg, trg_next)) layout when no local data_file is
    supplied (no network egress in this build)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        rng = np.random.RandomState({"train": 0, "dev": 1, "test": 2,
                                     "gen": 2}[mode])
        n = {"train": 2048, "dev": 256, "test": 256, "gen": 256}[mode]
        self.dict_size = dict_size
        self._pairs = []
        for _ in range(n):
            ls, lt = rng.randint(5, 30), rng.randint(5, 30)
            src = rng.randint(3, dict_size, (ls,)).astype("int64")
            trg = rng.randint(3, dict_size, (lt,)).astype("int64")
            trg_next = np.concatenate([trg[1:], [1]]).astype("int64")
            self._pairs.append((src, trg, trg_next))

    def get_dict(self, lang="en", reverse=False):
        d = {f"tok{i}": i for i in range(self.dict_size)}
        return {v: k for k, v in d.items()} if reverse else d

    def __getitem__(self, idx):
        return self._pairs[idx]

    def __len__(self):
        return len(self._pairs)


class WMT16(WMT14):
    """WMT16 multimodal en-de (`text/datasets/wmt16.py`); same synthetic
    layout with configurable vocab sizes."""

    def __init__(self, data_file=None, mode="train", src_dict_size=10000,
                 trg_dict_size=10000, lang="en", download=True):
        super().__init__(data_file, mode, max(src_dict_size, trg_dict_size),
                         download)
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (`paddle.text.viterbi_decode` over
    viterbi_decode_op): potentials [B, L, N], transition [N, N],
    lengths [B] -> (best scores [B], best paths [B, L]).

    Semantics are the reference op's exactly (test_viterbi_decode_op.py
    oracle): with include_bos_eos_tag the LAST tag is the virtual start
    (alpha starts at -1e4 except that tag) and the SECOND-TO-LAST is stop
    (trans[stop, tag] added on each sample's final step); per-sample
    lengths freeze alpha, and finished positions emit tag 0. TPU-first:
    the forward max-sum DP and the backpointer walk are two lax.scans —
    no host loop, static shapes, jit-safe.
    """
    import jax
    import jax.numpy as jnp
    from ..ops._dispatch import ensure_tensor, nondiff_op
    pots = ensure_tensor(potentials)
    trans = ensure_tensor(transition_params)
    lens = ensure_tensor(lengths)._value.astype("int32")

    def f(p, t):
        B, L, N = p.shape
        use_tag = include_bos_eos_tag

        def step(carry, logit):
            alpha, left = carry
            sc = alpha[:, :, None] + t[None]            # [B, N, N]
            bp = jnp.argmax(sc, axis=1)
            alpha_nxt = jnp.max(sc, axis=1) + logit
            mask = (left > 0)[:, None]
            alpha = jnp.where(mask, alpha_nxt, alpha)
            if use_tag:
                alpha = alpha + (left == 1)[:, None] * t[N - 2][None]
            return (alpha, left - 1), bp

        if use_tag:
            alpha0 = jnp.full((B, N), -1e4, p.dtype).at[:, -1].set(0.0)
            (alpha, left), bps = jax.lax.scan(
                step, (alpha0, lens), jnp.swapaxes(p, 0, 1))
            bps = bps[1:]                               # history from i>=1
        else:
            alpha0 = p[:, 0]
            (alpha, left), bps = jax.lax.scan(
                step, (alpha0, lens - 1), jnp.swapaxes(p[:, 1:], 0, 1))

        scores = jnp.max(alpha, -1)
        last_ids = jnp.argmax(alpha, -1).astype(jnp.int32)
        last_upd = last_ids * (left >= 0)

        def back(carry, hist):
            last_ids, left = carry
            left = left + 1
            upd = jnp.take_along_axis(hist, last_ids[:, None], 1)[:, 0]
            upd = upd.astype(jnp.int32) * (left > 0)
            eq0 = (left == 0)
            upd = upd * (1 - eq0) + last_ids * eq0
            new_last = upd + (left < 0) * last_ids
            return (new_last, left), upd

        (_, _), path_rev = jax.lax.scan(back, (last_ids, left), bps[::-1])
        path = jnp.concatenate([path_rev[::-1], last_upd[None]], axis=0)
        return scores, jnp.swapaxes(path, 0, 1).astype(jnp.int64)

    return nondiff_op(lambda a, b: f(a, b), [pots, trans])


class ViterbiDecoder:
    """Layer-style wrapper (`paddle.text.ViterbiDecoder`)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
