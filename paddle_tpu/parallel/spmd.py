"""SPMD hybrid-parallel train step — DP × TP × ZeRO × SP via GSPMD.

Reference parity: this one engine replaces several reference subsystems:
  - DP: dygraph `Reducer` bucketed allreduce (`imperative/reducer.cc`) — here
    gradients are reduced by XLA collectives fused into the backward;
  - TP: `TensorParallel` + mp_layers manual collectives — here sharding
    annotations (mp_layers.py) + GSPMD propagation;
  - ZeRO 1/2/3: `DygraphShardingOptimizer` / ShardingStage2/3
    (`fleet/meta_parallel/sharding/`) — here PartitionSpecs on optimizer
    slots (stage1/2) and parameters (stage3); XLA emits the reduce-scatter +
    all-gather pattern with buffer donation standing in for param2buffer
    slicing (`sharding_stage3.py:308-348`);
  - AMP O2: params kept fp32, cast to bf16 inside the step (master weights).

One `jax.jit` with in/out shardings over the HybridCommunicateGroup mesh:
forward + backward + optimizer in a single XLA program, collectives on ICI.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import monitor as _monitor
from .. import obs as _obs
from ..obs import memory as _mem
from ..core import compile_cache as _cc
from ..core import executable as _exe
from ..core import random as rnd
from ..core.tensor import Tensor
from ..jit.functional import functional_call, split_state
from .topology import get_mesh


def _shard_biggest_axis(shape, axis_name, axis_size):
    """Pick the largest dim divisible by axis_size to shard (ZeRO slicing)."""
    if not shape:
        return None
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % axis_size == 0 and shape[i] >= axis_size:
            spec = [None] * len(shape)
            spec[i] = axis_name
            return tuple(spec)
    return None


class SPMDTrainStep:
    def __init__(self, model, loss_fn: Callable, optimizer, mesh: Optional[Mesh] = None,
                 sharding_stage: int = 0, amp_dtype=None, donate: bool = True,
                 batch_specs: Optional[Sequence] = None, n_model_inputs=None,
                 grad_reduction: str = "gspmd",
                 bucket_bytes: Optional[int] = None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh or get_mesh()
        if self.mesh is None:
            raise ValueError("SPMDTrainStep requires a mesh (fleet.init or create_mesh)")
        self.sharding_stage = sharding_stage
        self.amp_dtype = amp_dtype
        self._donate = donate
        self._batch_specs = batch_specs
        self._n_model_inputs = n_model_inputs
        # "gspmd": the compiler inserts/fuses the gradient reduction.
        # "bucketed": explicit backward-interleaved per-bucket allreduce via
        # parallel.reducer.Reducer inside shard_map over the dp axis (the
        # reference imperative Reducer role) — the collectives are visible
        # to collective_signature()/tpu-lint instead of compiler-hidden.
        if grad_reduction not in ("gspmd", "bucketed"):
            raise ValueError(f"grad_reduction must be 'gspmd' or 'bucketed', "
                             f"got {grad_reduction!r}")
        self.grad_reduction = grad_reduction
        self._bucket_bytes = bucket_bytes  # None -> FLAGS_dp_bucket_mb
        self.reducer = None
        self._jitted = None
        self._slots = None
        # per-step device scalars: lr re-uploads only on value change, the
        # step counter t rides as donated carry state through the program
        self._lr_arr = None
        self._lr_host = None
        self._t_arr = None
        self._t_host = None
        # executable substrate: batch-signature ledger (novelty + retrace
        # accounting — previously only the FIRST build was attributed) and
        # per-signature persistent-cache callables
        self._ledger = _exe.ExecutableLedger("spmd_train_step")

    # ---- sharding policies ----
    def _data_axes(self):
        axes = [a for a in ("dp", "sharding") if a in self.mesh.shape]
        return tuple(axes) if axes else None

    def _param_spec(self, p):
        if p.dist_attr is not None:
            spec = tuple(a if (a is None or a in self.mesh.shape) else None
                         for a in p.dist_attr)
            if self.sharding_stage >= 3 and "sharding" in self.mesh.shape and \
                    all(a is None for a in spec):
                s3 = _shard_biggest_axis(tuple(p.shape), "sharding",
                                         self.mesh.shape["sharding"])
                return P(*s3) if s3 else P(*spec)
            return P(*spec)
        if self.sharding_stage >= 3 and "sharding" in self.mesh.shape:
            s3 = _shard_biggest_axis(tuple(p.shape), "sharding",
                                     self.mesh.shape["sharding"])
            if s3:
                return P(*s3)
        return P()

    def _slot_spec(self, p, pspec):
        if self.sharding_stage >= 1 and "sharding" in self.mesh.shape:
            if self.sharding_stage >= 3:
                return pspec  # slots follow sharded params
            s = _shard_biggest_axis(tuple(p.shape), "sharding",
                                    self.mesh.shape["sharding"])
            if s:
                return P(*s)
        return pspec

    def _batch_spec(self, ndim, i):
        if self._batch_specs is not None and i < len(self._batch_specs):
            sp = self._batch_specs[i]
            return sp if isinstance(sp, P) else P(*sp)
        ax = self._data_axes()
        return P(ax) if ax else P()

    # ---- build ----
    def _build(self, batch_arrs):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        trainable, frozen = split_state(model)
        self._pnames, self._bnames = list(trainable), list(frozen)
        ptensors = [trainable[n] for n in self._pnames]
        btensors = [frozen[n] for n in self._bnames]
        optimizer._parameter_list = optimizer._parameter_list or ptensors
        self._slots = optimizer.init_state(ptensors)
        pnames, bnames = self._pnames, self._bnames
        amp_dtype = self.amp_dtype
        mesh = self.mesh
        # jitted-path FLAGS_check_nan_inf (see jit/train_step.py): finite
        # flags traced into the SPMD executable, captured at build time
        from ..core import flags as _flags
        nan_check = bool(_flags.flag("check_nan_inf"))
        self._nan_check = nan_check

        pspecs = [self._param_spec(p) for p in ptensors]
        sspecs = [{k: self._slot_spec(p, ps) for k in s}
                  for p, ps, s in zip(ptensors, pspecs, self._slots)]
        bspecs = [P() for _ in btensors]
        n_mi = self._n_model_inputs
        if n_mi is None:
            n_mi = len(batch_arrs) if len(batch_arrs) <= 1 else len(batch_arrs) - 1
        self._n_mi = n_mi
        in_batch_specs = [self._batch_spec(a.ndim, i) for i, a in enumerate(batch_arrs)]

        def step_body(params, slots, buffers, step_key, lr, t, inputs,
                      labels, reducer=None):
            """Shared fwd+bwd+update core. With a reducer, grads are
            reduced per size-capped bucket in backward order (explicit
            collectives the latency-hiding scheduler can overlap with the
            remaining backward); without one, GSPMD owns the reduction."""
            rnd.push_trace_key(step_key)
            try:
                def fwd(ps):
                    from ..jit.functional import amp_functional_call
                    out = amp_functional_call(model, pnames, ps, bnames,
                                              buffers, inputs, amp_dtype)
                    outs = [Tensor(o) for o in out] if isinstance(out, (list, tuple)) \
                        else [Tensor(out)]
                    loss = loss_fn(*outs, *[Tensor(l) for l in labels])
                    return loss._value if isinstance(loss, Tensor) else loss

                loss, grads = jax.value_and_grad(fwd)(params)
                if reducer is not None:
                    grads = reducer.reduce(grads)
                    from jax import lax as _lax
                    loss = _lax.pmean(loss, reducer.axis)
                new_params, new_slots = optimizer.functional_update(
                    params, grads, slots, lr, t, params_meta=ptensors)
                if nan_check:
                    bad = jnp.stack(
                        [~jnp.isfinite(loss)]
                        + [~jnp.all(jnp.isfinite(g)) for g in grads])
                    return new_params, new_slots, loss, t + 1.0, bad
                return new_params, new_slots, loss, t + 1.0, None
            finally:
                rnd.pop_trace_key()

        use_reducer = self.grad_reduction == "bucketed"
        if use_reducer:
            if "dp" not in mesh.shape:
                raise ValueError("grad_reduction='bucketed' needs a 'dp' "
                                 "mesh axis (the reducer allreduces over it)")
            if self.sharding_stage != 0 or len(mesh.shape) != 1:
                raise ValueError(
                    "grad_reduction='bucketed' supports the pure-DP regime "
                    "(1-axis dp mesh, sharding_stage=0); hybrid layouts use "
                    "grad_reduction='gspmd' where the compiler owns the "
                    "reduction")
            bad_specs = [n for n, s in zip(self._pnames, pspecs) if s != P()]
            if bad_specs:
                raise ValueError("bucketed reduction requires replicated "
                                 f"params; sharded: {bad_specs[:3]}")
            from .reducer import Reducer
            self.reducer = Reducer(ptensors, axis="dp",
                                   bucket_bytes=self._bucket_bytes)

            def pure(params, slots, buffers, rng_key, lr, t, batch):
                from jax.experimental.shard_map import shard_map

                def body(params, slots, buffers, rng_key, lr, t, *batch):
                    inputs, labels = batch[:n_mi], batch[n_mi:]
                    return step_body(params, slots, buffers, rng_key, lr, t,
                                     inputs, labels, reducer=self.reducer)

                in_specs = ([P() for _ in params],
                            [{k: P() for k in d} for d in slots],
                            [P() for _ in buffers],
                            P(), P(), P(),
                            *[P(*s) if not isinstance(s, P) else s
                              for s in in_batch_specs])
                out_specs = ([P() for _ in params],
                             [{k: P() for k in d} for d in slots],
                             P(), P(),
                             P() if nan_check else None)
                return shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)(
                    params, slots, buffers, rng_key, lr, t, *batch)
        else:
            def pure(params, slots, buffers, rng_key, lr, t, batch):
                inputs, labels = batch[:n_mi], batch[n_mi:]
                return step_body(params, slots, buffers, rng_key, lr, t,
                                 inputs, labels)

        def ns(spec):
            return NamedSharding(mesh, spec)

        in_sh = ([ns(s) for s in pspecs],
                 [{k: ns(v) for k, v in d.items()} for d in sspecs],
                 [ns(s) for s in bspecs],
                 None, ns(P()), ns(P()),
                 [ns(s) for s in in_batch_specs])
        out_sh = ([ns(s) for s in pspecs],
                  [{k: ns(v) for k, v in d.items()} for d in sspecs],
                  ns(P()),
                  ns(P()),
                  ns(P()) if nan_check else None)
        # donate params (0), slots (1) and the t carry (5)
        donate = (0, 1, 5) if self._donate else ()
        self._donate_argnums = donate
        self._pure = pure   # unjitted typed-key body: collective_signature
        # persistent-cache mode: raw-key-data program boundary (jax.export
        # cannot serialize typed PRNG key avals — TrainStep._build regime)
        self._raw_key = _cc.enabled()
        jit_pure = pure
        if self._raw_key:
            def jit_pure(params, slots, buffers, key_data, lr, t, batch):
                return pure(params, slots, buffers,
                            jax.random.wrap_key_data(key_data), lr, t, batch)
        self._jitted = jax.jit(jit_pure, in_shardings=in_sh,
                               out_shardings=out_sh, donate_argnums=donate)
        self._pspecs = pspecs
        self._sspecs = sspecs
        from .. import analysis as _analysis
        if _analysis._ENABLED:
            _analysis.lint_traced(getattr(model, "forward", model),
                                  "spmd_train_step")
            _analysis.lint_traced(loss_fn, "spmd_train_step")

        # place params/slots/buffers on the mesh once (avoids per-step resharding)
        for p, spec in zip(ptensors, pspecs):
            p._value = jax.device_put(p._value, ns(spec))
        self._slots = [{k: jax.device_put(v, ns(d[k])) for k, v in s.items()}
                       for s, d in zip(self._slots, sspecs)]
        for b, spec in zip(btensors, bspecs):
            b._value = jax.device_put(b._value, ns(spec))
        pending = getattr(self, "_pending_state", None)
        if pending is not None:  # set_state_dict before the first step
            self._pending_state = None
            self._apply_state(pending)
        if _mem._ENABLED:
            self._tag_state()

    def _tag_state(self):
        """(Re-)tag the mesh-resident loop state for the live-buffer census
        (donation kills the old buffers' tags — see TrainStep._tag_state)."""
        trainable, frozen = split_state(self.model)
        _mem.tag("params", [trainable[n]._value for n in self._pnames],
                 origin="SPMDTrainStep")
        _mem.tag("opt_slots", self._slots, origin="SPMDTrainStep")
        _mem.tag("model_buffers", [frozen[n]._value for n in self._bnames],
                 origin="SPMDTrainStep")
        if self._t_arr is not None:
            _mem.tag("step_state", [self._t_arr], origin="SPMDTrainStep")

    def collective_signature(self, *batch):
        """The step's static collective sequence (tpu-lint collective-order
        rule): trace the unjitted step body and extract every explicit
        collective as `analysis.graph.CollectiveDesc`s. Feed the per-rank /
        per-stage results to `analysis.verify_collective_order` to prove
        the sequences agree BEFORE a pod slice deadlocks on a divergence.
        (GSPMD-inserted collectives are compiler-chosen and not part of the
        static signature; explicit ones — mp/pp/sp ops traced through
        `parallel.collective` inside shard_map regions — are.)"""
        arrs = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                for b in batch]
        if self._jitted is None:
            self._build(arrs)
        trainable, frozen = split_state(self.model)
        params = [trainable[n]._value for n in self._pnames]
        buffers = [frozen[n]._value for n in self._bnames]
        key = rnd.default_generator().next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(self.optimizer._step_count + 1, jnp.float32)
        from ..analysis.graph import collective_sequence
        return collective_sequence(self._pure, params, self._slots, buffers,
                                   key, lr, t, arrs)

    # ---- full loop-state capture (guard plane: preemption-safe resume) ----
    def named_param_arrays(self):
        """name -> device array for every trainable param (desync
        fingerprints; no copy)."""
        trainable, _ = split_state(self.model)
        names = self._pnames if self._jitted is not None else list(trainable)
        return {n: trainable[n]._value for n in names}

    def state_dict(self):
        """Host-side copy of params + optimizer slots + step counter. The
        per-step rng key is drawn from the global generator (capture it
        with core.random.get_rng_state alongside this dict — the guard
        checkpoint does)."""
        if self._jitted is None:
            raise RuntimeError("SPMDTrainStep.state_dict() requires a built "
                               "step — run at least one step first")
        trainable, _ = split_state(self.model)
        return {
            "kind": "spmd_train_step",
            "params": {n: np.asarray(trainable[n]._value)
                       for n in self._pnames},
            "slots": [{k: np.asarray(v) for k, v in s.items()}
                      for s in self._slots],
            "step_count": int(self.optimizer._step_count),
        }

    def set_state_dict(self, sd):
        if self._jitted is None:
            # applied at the end of _build, after shardings exist
            self._pending_state = sd
            self.optimizer._step_count = int(sd["step_count"])
            return
        self._apply_state(sd)

    def _apply_state(self, sd):
        from jax.sharding import NamedSharding

        def ns(spec):
            return NamedSharding(self.mesh, spec)

        trainable, _ = split_state(self.model)
        params = sd["params"]
        for n, spec in zip(self._pnames, self._pspecs):
            if n in params:
                trainable[n]._value = jax.device_put(
                    jnp.asarray(params[n]), ns(spec))
        self._slots = [{k: jax.device_put(jnp.asarray(v), ns(d[k]))
                        for k, v in s.items()}
                       for s, d in zip(sd["slots"], self._sspecs)]
        self.optimizer._step_count = int(sd["step_count"])

    # ---- per-step device scalars (no fresh float() feeds per step) ----
    def _lr_scalar(self):
        """lr as a mesh-replicated cached scalar: H2D only on value change."""
        lr_val = self.optimizer.get_lr()
        if lr_val != self._lr_host or self._lr_arr is None:
            self._lr_host = lr_val
            self._lr_arr = jax.device_put(
                jnp.asarray(lr_val, jnp.float32),
                NamedSharding(self.mesh, P()))
        return self._lr_arr

    def _t_scalar(self):
        """Step counter as donated device carry (the program returns t+1);
        the host mirror catches external _step_count writes (guard
        rollback/resume) and refreshes the carry from the host."""
        expected = float(self.optimizer._step_count + 1)
        if self._t_arr is None or self._t_host != expected:
            self._t_arr = jax.device_put(
                jnp.asarray(expected, jnp.float32),
                NamedSharding(self.mesh, P()))
            self._t_host = expected
        return self._t_arr

    def input_shardings(self, *batch):
        """NamedShardings for the step's batch arguments — what the
        io.prefetch feeder uses so its device_put stages each batch
        DIRECTLY into the layout the executable consumes (no resharding
        on the step's critical path). Builds the step if needed."""
        arrs = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                for b in batch]
        if self._jitted is None:
            self._build(arrs)
        return [NamedSharding(self.mesh, self._batch_spec(a.ndim, i))
                for i, a in enumerate(arrs)]

    def __call__(self, *batch):
        with _obs.step_record():
            with _obs.phase("h2d"):
                arrs = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                        for b in batch]
            first = self._jitted is None
            if first:
                with _obs.phase("build"):
                    self._build(arrs)
            trainable, frozen = split_state(self.model)
            params = [trainable[n]._value for n in self._pnames]
            buffers = [frozen[n]._value for n in self._bnames]
            key = rnd.default_generator().next_key()
            if self._raw_key:
                key = jax.random.key_data(key)
            lr = self._lr_scalar()
            t = self._t_scalar()
            if _mem._ENABLED:
                _mem.tag("activations", arrs, origin="SPMDTrainStep.batch")
            sig, novel = None, first
            if _monitor._ENABLED or _obs._TL_ENABLED or _cc.enabled():
                sig = _monitor.arg_signature(arrs)
                novel = self._ledger.note(sig)
            # GSPMD folds the collectives INTO the executable, so the
            # timeline cannot fence them apart from compute here — the
            # device_compute phase is the whole sharded step; explicit
            # eager collectives (parallel/collective.py) get their own
            # `collective` phase.
            with _exe.booking("spmd_train_step") as bk:
                call = self._jitted
                if sig is not None:
                    cached = self._ledger.get(sig)
                    if cached is not None:
                        call = cached
                    elif novel:
                        if _cc.enabled():
                            call, source = _exe.acquire(
                                "spmd_train_step", self._jitted,
                                (params, self._slots, buffers, key, lr, t,
                                 arrs),
                                donate=self._donate_argnums,
                                label="SPMDTrainStep",
                                mesh_shape=dict(self.mesh.shape))
                            self._ledger.put(sig, call)
                            if source == "fresh":
                                bk.compiled()
                        else:
                            bk.compiled()
                elif first:
                    bk.compiled()
                with _exe.dispatch_guard(
                        "SPMDTrainStep",
                        report=lambda: _obs.executable_memory(
                            self._jitted.lower(params, self._slots, buffers,
                                               key, lr, t, arrs).compile())):
                    new_params, self._slots, loss, new_t, bad = call(
                        params, self._slots, buffers, key, lr, t, arrs)
                if _obs._TL_ENABLED:
                    jax.block_until_ready(loss)
            # commit before the debug raise — old buffers were donated
            for n, v in zip(self._pnames, new_params):
                trainable[n]._value = v
            self._t_arr = new_t
            self._t_host = self._t_host + 1.0
            self.optimizer._step_count += 1
            if _mem._ENABLED:
                self._tag_state()
            from ..jit.train_step import raise_nonfinite
            raise_nonfinite(bad, self._pnames, "jitted SPMD train step")
            return Tensor(loss)

    def cost_analysis(self, *batch):
        """Compiler-attributed {flops, bytes_accessed} for the sharded step
        executable (see jit.TrainStep.cost_analysis). Per-device numbers:
        XLA reports the cost of one shard of the SPMD program."""
        arrs = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                for b in batch]
        if self._jitted is None:
            self._build(arrs)
        trainable, frozen = split_state(self.model)
        params = [trainable[n]._value for n in self._pnames]
        buffers = [frozen[n]._value for n in self._bnames]
        key = rnd.default_generator().next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(self.optimizer._step_count + 1, jnp.float32)
        lowered = self._jitted.lower(params, self._slots, buffers, key, lr,
                                     t, arrs)
        return _obs.executable_cost(lowered.compile())

    def memory_report(self, *batch):
        """Compiler-reported memory breakdown for the sharded step
        executable (see jit.TrainStep.memory_report). Per-device numbers:
        XLA reports one shard of the SPMD program."""
        arrs = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                for b in batch]
        if self._jitted is None:
            self._build(arrs)
        trainable, frozen = split_state(self.model)
        params = [trainable[n]._value for n in self._pnames]
        buffers = [frozen[n]._value for n in self._bnames]
        key = rnd.default_generator().next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(self.optimizer._step_count + 1, jnp.float32)
        lowered = self._jitted.lower(params, self._slots, buffers, key, lr,
                                     t, arrs)
        return _obs.executable_memory(lowered.compile())
