"""Bucketed backward-interleaved gradient reduction for data parallelism.

Reference parity: the dygraph `Reducer` (`imperative/reducer.cc`, PAPER.md
§1 row 6): group gradients into size-capped buckets
(`FLAGS_dp_bucket_mb`, reference DataParallel comm_buffer_size) and issue
one fused allreduce per bucket AS ITS GRADS BECOME READY during the
backward, so communication overlaps the remaining backward compute instead
of serializing after it.

TPU-native version: there is no eager hook stream — the whole step is one
traced program — so "as grads become ready" is expressed STRUCTURALLY:
buckets are ordered by reverse parameter order (the backward produces the
last layer's grads first), and each bucket's collective depends ONLY on its
own members' grads. XLA's latency-hiding scheduler can therefore start
bucket k's reduce while the grads of buckets k+1.. are still being
computed — the compiler plays the role of the reference's overlapping comm
stream. One end-of-step reduction over the whole tree (a single concat +
psum) would instead serialize: nothing can start until the LAST grad exists.

Used by `SPMDTrainStep(grad_reduction="bucketed")`, which runs the step
inside shard_map over the dp axis with explicit per-bucket collectives —
visible to `collective_signature()` / tpu-lint collective-order
verification, unlike GSPMD-inserted reductions.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core import flags as _flags
from ..core.jaxcompat import axis_size as _axis_size
from .collective import _record

__all__ = ["Reducer"]


def _nbytes(shape, dtype) -> int:
    return int(np.prod(shape)) * np.dtype(dtype).itemsize if shape \
        else np.dtype(dtype).itemsize


class Reducer:
    """Size-capped gradient buckets over a parameter list, reduced one
    collective per bucket in backward (reverse-parameter) order.

    `params` supplies shape/dtype metadata only (Parameter/Tensor or bare
    arrays). Buckets never mix dtypes (a concat must be homogeneous; the
    reference buckets by dtype too).
    """

    def __init__(self, params: Sequence, axis: str = "dp",
                 bucket_bytes: Optional[int] = None, mean: bool = True):
        self.axis = axis
        self.mean = mean
        if bucket_bytes is None:
            bucket_bytes = int(_flags.flag("dp_bucket_mb")) << 20
        self.bucket_bytes = max(1, int(bucket_bytes))
        shapes = [tuple(getattr(p, "shape", np.shape(p))) for p in params]
        dtypes = [np.dtype(str(getattr(p, "dtype", np.asarray(p).dtype)))
                  for p in params]
        self._shapes, self._dtypes = shapes, dtypes
        self._buckets = self._build(shapes, dtypes)

    def _build(self, shapes, dtypes) -> List[List[int]]:
        buckets: List[List[int]] = []
        cur: List[int] = []
        cur_bytes = 0
        cur_dtype = None
        # reverse order = backward production order: the last parameters'
        # grads exist first, so their bucket's collective can issue while
        # earlier layers' grads are still being computed
        for i in reversed(range(len(shapes))):
            nb = _nbytes(shapes[i], dtypes[i])
            if cur and (dtypes[i] != cur_dtype
                        or cur_bytes + nb > self.bucket_bytes):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nb
            cur_dtype = dtypes[i]
        if cur:
            buckets.append(cur)
        return buckets

    # ---- introspection (tests / docs) ----
    def bucket_layout(self) -> List[List[int]]:
        """Original-order parameter indices per bucket, in issue order."""
        return [list(b) for b in self._buckets]

    def bucket_sizes(self) -> List[int]:
        return [sum(_nbytes(self._shapes[i], self._dtypes[i]) for i in b)
                for b in self._buckets]

    # ---- the traced reduction ----
    def reduce(self, grads: Sequence) -> List:
        """Reduce a grad list (aligned with the constructor's params) across
        `self.axis`: one flattened-concat psum per bucket, buckets issued in
        backward order. Must run inside a shard_map region binding the axis
        (SPMDTrainStep's bucketed mode); mean=True averages over the axis.
        Returns the reduced grads in ORIGINAL parameter order."""
        n = _axis_size(self.axis)
        scale = 1.0 / n if self.mean else None
        out: List = [None] * len(grads)
        for bucket in self._buckets:
            if len(bucket) == 1:
                i = bucket[0]
                _record("c_allreduce_bucket", grads[i])
                red = lax.psum(grads[i], self.axis)
                out[i] = red * jnp.asarray(scale, red.dtype) if scale else red
                continue
            flat = jnp.concatenate([jnp.ravel(grads[i]) for i in bucket])
            _record("c_allreduce_bucket", flat)
            red = lax.psum(flat, self.axis)
            if scale:
                red = red * jnp.asarray(scale, red.dtype)
            off = 0
            for i in bucket:
                size = int(np.prod(self._shapes[i])) if self._shapes[i] else 1
                out[i] = red[off:off + size].reshape(self._shapes[i])
                off += size
        return out
