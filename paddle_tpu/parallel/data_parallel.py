"""DataParallel wrapper.

Reference parity: `python/paddle/fluid/dygraph/parallel.py:400` (DataParallel
→ C++ Reducer bucketed allreduce overlapped with backward).

TPU-native: in the single-controller model there are no per-rank replicas to
reduce across eagerly — data parallelism is batch sharding over the 'dp'
mesh axis inside the jitted step, with XLA fusing the gradient all-reduce
into the backward (the Reducer's overlap, done by the compiler). The wrapper
therefore (a) passes forward through unchanged for eager use, and (b) marks
the model so TrainStep/SPMDTrainStep shard the batch.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .topology import get_mesh


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, hcg=None,
                 group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self.hcg = hcg
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, state_dict, *a, **kw):
        return self._layers.set_state_dict(state_dict, *a, **kw)

    def scale_loss(self, loss):
        return loss  # grads averaged inside the jitted step (pmean semantics)

    def apply_collective_grads(self):
        pass  # XLA inserts the collective in the compiled backward

    @property
    def _inner_layers(self):
        return self._layers
