"""Collective communication API.

Reference parity: `python/paddle/distributed/collective.py` (all_reduce:289,
all_gather, broadcast, reduce, scatter, alltoall, send/recv, barrier,
new_group:208) over the `operators/collective/c_*` op corpus.

TPU-native: a collective is an XLA op over a MESH AXIS, not an NCCL ring.
Each function has two execution regimes, detected automatically:
  1. inside an SPMD region (shard_map'd / pjit-manual code where the mesh
     axis name is bound) — lowers to lax.psum / all_gather / ppermute /
     all_to_all riding ICI;
  2. eager, single-controller — the global array is already replicated or
     sharded across the mesh; reductions become jnp ops on the global view
     (XLA inserts the transfer), so user code behaves like rank-0 semantics
     of the reference.
The `group` argument accepts a mesh axis name (str) — the `ring_id` of the
TPU world. `ReduceOp` mirrors the reference enum.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import monitor as _monitor
from .. import obs as _obs
from ..core.jaxcompat import axis_size as _axis_size
from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op


def _record(name: str, t) -> None:
    """Monitor + flight-recorder planes: count the collective and its
    logical payload bytes. Works on tracers too (shape/dtype are static),
    so SPMD-region collectives are accounted once per trace. The flight
    recorder keeps the recent (name, bytes) sequence — after a wedged
    collective, the dump shows what the rank issued leading up to it."""
    if not (_monitor._ENABLED or _obs._FR_ENABLED):
        return
    v = getattr(t, "_value", t)
    try:
        nbytes = int(np.prod(v.shape)) * np.dtype(v.dtype).itemsize
    except Exception:
        nbytes = 0
    if _monitor._ENABLED:
        _monitor.record_collective(name, nbytes)
    if _obs._FR_ENABLED:
        _obs.record_collective(name, nbytes)


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """Mesh-axis-backed communication group (ring_id → axis name)."""

    def __init__(self, axis_name: str, nranks: int = 1, ring_id: int = 0):
        self.axis_name = axis_name
        self.nranks = nranks
        self.id = ring_id

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return f"Group(axis={self.axis_name}, nranks={self.nranks})"


_GROUPS = {}


def new_group(ranks=None, backend=None, axis_name: Optional[str] = None):
    """Create a group. TPU-native: groups are mesh axes; pass axis_name, or
    ranks spanning a full axis of the current mesh."""
    from .topology import get_mesh
    mesh = get_mesh()
    name = axis_name or (f"g{len(_GROUPS)}" if ranks else "dp")
    n = len(ranks) if ranks else (mesh.shape.get(name, 1) if mesh else 1)
    g = Group(name, n, ring_id=len(_GROUPS) + 1)
    _GROUPS[g.id] = g
    return g


def _axis(group):
    if group is None:
        return None
    if isinstance(group, Group):
        return group.axis_name
    if isinstance(group, str):
        return group
    return None


def _in_spmd(axis_name) -> bool:
    """True when `axis_name` is bound in the current trace (inside shard_map)."""
    if axis_name is None:
        return False
    try:
        _axis_size(axis_name)
        return True
    except Exception:
        return False


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place (paddle semantics): tensor payload replaced with the result."""
    t = ensure_tensor(tensor)
    _record("c_allreduce", t)
    ax = _axis(group) or "dp"
    if _in_spmd(ax):
        red = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax, ReduceOp.MIN: lax.pmin}
        with _obs.phase("collective"):
            if op == ReduceOp.AVG:
                out = run_op(lambda a: lax.pmean(a, ax), [t], "c_allreduce_avg")
            else:
                fn = red.get(op)
                if fn is None:  # PROD via exp-sum-log not safe; use reduce then broadcast
                    out = run_op(lambda a: jnp.exp(lax.psum(jnp.log(a), ax)), [t],
                                 "c_allreduce_prod")
                else:
                    out = run_op(lambda a: fn(a, ax), [t], "c_allreduce")
        from ..ops._dispatch import inplace_from
        return inplace_from(t, out)
    # eager single-controller: the global array already holds the logical value
    return t


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    t = ensure_tensor(tensor)
    _record("c_allgather", t)
    ax = _axis(group) or "dp"
    if _in_spmd(ax):
        with _obs.phase("collective"):
            out = run_op(lambda a: lax.all_gather(a, ax, tiled=False), [t],
                         "c_allgather")
        n = _axis_size(ax)
        parts = [Tensor(out._value[i]) for i in range(n)]
        if tensor_list is not None:
            tensor_list.extend(parts)
        return out
    if tensor_list is not None:
        tensor_list.append(t)
    return t


def all_gather_object(obj_list, obj, group=None):
    obj_list.append(obj)
    return obj_list


def store_all_gather_object(store, key: str, obj, rank: int, world_size: int,
                            timeout_s: float = 30.0, poll_s: float = 0.01):
    """Multi-controller all-gather of a small JSON-able object through a
    rendezvous store (TCPStore, or any set/get mapping). The eager
    collectives above cover the single-controller regime where every rank
    IS this process; cross-PROCESS exchange (guard desync fingerprints,
    membership votes) goes through the store the job already rendezvoused
    on. Returns {rank: obj}; raises TimeoutError when a peer's value does
    not appear within `timeout_s` (a hang, not a desync — callers must not
    blame a rank for being slow)."""
    import json as _json
    import time as _time
    with _obs.phase("collective"):
        store.set(f"{key}:{rank}", _json.dumps(obj))
        if _monitor._ENABLED:
            _monitor.count("c_store_allgather_obj")
        if _obs._FR_ENABLED:
            _obs.record_collective("store_allgather_obj", 0)
        out = {}
        deadline = _time.monotonic() + timeout_s
        for r in range(world_size):
            while True:
                try:
                    raw = store.get(f"{key}:{r}")
                    break
                except Exception:
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            f"store_all_gather_object: rank {r} never published "
                            f"{key!r} within {timeout_s}s")
                    _time.sleep(poll_s)
            out[r] = _json.loads(raw.decode() if isinstance(raw, (bytes, bytearray))
                                 else raw)
    return out


def reduce_scatter(tensor, tensor_list_or_input, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax = _axis(group) or "dp"
    src = tensor_list_or_input
    if isinstance(src, (list, tuple)):
        from ..ops.manipulation import concat
        src = concat(list(src), axis=0)
    t = ensure_tensor(src)
    _record("c_reducescatter", t)
    if _in_spmd(ax):
        with _obs.phase("collective"):
            out = run_op(lambda a: lax.psum_scatter(a, ax, tiled=True), [t],
                         "c_reducescatter")
        if tensor is not None:
            tensor._value = out._value
        return out
    return t


def broadcast(tensor, src=0, group=None, sync_op=True):
    t = ensure_tensor(tensor)
    _record("c_broadcast", t)
    ax = _axis(group) or "dp"
    if _in_spmd(ax):
        idx = lax.axis_index(ax)
        out = run_op(
            lambda a: lax.psum(jnp.where(idx == src, a, jnp.zeros_like(a)), ax),
            [t], "c_broadcast")
        from ..ops._dispatch import inplace_from
        return inplace_from(t, out)
    return t


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)  # SPMD: every shard holds result


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = _axis(group) or "dp"
    _record("c_scatter", ensure_tensor(tensor))
    if tensor_list is not None and _in_spmd(ax):
        from ..ops.manipulation import stack
        stacked = stack(list(tensor_list), axis=0)
        idx = lax.axis_index(ax)
        out = run_op(lambda a: a[idx], [stacked], "c_scatter")
        tensor._value = out._value
        return tensor
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    ax = _axis(group) or "mp"
    if isinstance(in_tensor_list, (list, tuple)):
        from ..ops.manipulation import stack
        src = stack(list(in_tensor_list), axis=0)
    else:
        src = ensure_tensor(in_tensor_list)
    _record("alltoall", src)
    if _in_spmd(ax):
        out = run_op(lambda a: lax.all_to_all(a, ax, split_axis=0, concat_axis=0,
                                              tiled=False), [src], "alltoall")
        if out_tensor_list is not None:
            n = _axis_size(ax)
            out_tensor_list.extend(Tensor(out._value[i]) for i in range(n))
        return out
    if out_tensor_list is not None and isinstance(in_tensor_list, (list, tuple)):
        out_tensor_list.extend(in_tensor_list)
    return src


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    t = ensure_tensor(in_tensor)
    _record("alltoall_single", t)
    ax = _axis(group) or "mp"
    if _in_spmd(ax):
        out = run_op(lambda a: lax.all_to_all(a, ax, split_axis=0, concat_axis=0,
                                              tiled=True), [t], "alltoall_single")
        if out_tensor is not None:
            out_tensor._value = out._value
        return out
    return t


def send(tensor, dst=0, group=None, sync_op=True):
    """SPMD p2p: expressed as ppermute to the destination stage (pipeline use)."""
    t = ensure_tensor(tensor)
    _record("send_v2", t)
    ax = _axis(group) or "pp"
    if _in_spmd(ax):
        n = _axis_size(ax)
        perm = [(i, (i + 1) % n) for i in range(n)]
        return run_op(lambda a: lax.ppermute(a, ax, perm), [t], "send_v2")
    return t


def recv(tensor, src=0, group=None, sync_op=True):
    return ensure_tensor(tensor)


isend = send
irecv = recv


def p2p_shift(x, group="pp", shift=1):
    """ppermute neighbour shift — the TPU-native partial_send/recv."""
    t = ensure_tensor(x)
    _record("p2p_shift", t)
    ax = _axis(group) or "pp"
    n = _axis_size(ax)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return run_op(lambda a: lax.ppermute(a, ax, perm), [t], "p2p_shift")


def barrier(group=None):
    # single-controller SPMD: dispatch order already serializes; sync devices
    for d in jax.devices():
        pass
    jnp.zeros(()).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    ensure_tensor(tensor).block_until_ready()


def get_group(ring_id=0):
    return _GROUPS.get(ring_id)


# ---- model-parallel helpers (collective.py:793-927 parity) ----
def _c_identity(tensor, group=None):
    return ensure_tensor(tensor)


def _mp_allreduce(tensor, op=ReduceOp.SUM, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    return all_reduce(tensor, op, group or "mp")


def _c_concat(tensor, group=None):
    t = ensure_tensor(tensor)
    _record("c_concat", t)
    ax = _axis(group) or "mp"
    if _in_spmd(ax):
        return run_op(lambda a: lax.all_gather(a, ax, axis=a.ndim - 1, tiled=True),
                      [t], "c_concat")
    return t


def _c_split(tensor, group=None):
    t = ensure_tensor(tensor)
    _record("c_split", t)
    ax = _axis(group) or "mp"
    if _in_spmd(ax):
        n = _axis_size(ax)
        idx = lax.axis_index(ax)

        def f(a):
            sz = a.shape[-1] // n
            return lax.dynamic_slice_in_dim(a, idx * sz, sz, axis=a.ndim - 1)

        return run_op(f, [t], "c_split")
    return t
