"""Process/device topology → `jax.sharding.Mesh`.

Reference parity: `python/paddle/distributed/fleet/base/topology.py:36`
(CommunicateTopology over the 4-D grid [data, pipe, sharding, model]) and
HybridCommunicateGroup (`topology.py:117`) which creates per-axis comm
groups. TPU-native: the grid IS a `jax.sharding.Mesh` whose axis order maps
outer→DCN-ish, inner→ICI-adjacent (mp/sp innermost so tensor-parallel
collectives ride the fastest links — scaling-book recipe); "comm groups"
become mesh axis names instead of NCCL rings.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
from jax.sharding import Mesh

_GLOBAL_HCG = [None]
_GLOBAL_MESH = [None]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coords = [kwargs[n] for n in self._parallel_names]
        return int(np.ravel_multi_index(coords, self._dims))

    def get_coord(self, rank):
        return tuple(int(c) for c in np.unravel_index(rank, self._dims))


# paddle axis name -> mesh axis name
_AXIS_MAP = {"data": "dp", "pipe": "pp", "sharding": "sharding", "model": "mp",
             "sep": "sp"}


class HybridCommunicateGroup:
    """Builds the device mesh for the hybrid strategy.

    Axis order (outer→inner): dp, pp, sharding, mp[, sp] — mp (and sp)
    innermost so their collectives map to adjacent ICI neighbours.
    """

    def __init__(self, strategy=None, hybrid_configs: Optional[Dict] = None,
                 devices=None):
        cfg = hybrid_configs or (strategy.hybrid_configs if strategy else {})
        self.dp_degree = int(cfg.get("dp_degree", 1))
        self.pp_degree = int(cfg.get("pp_degree", 1))
        self.sharding_degree = int(cfg.get("sharding_degree", 1))
        self.mp_degree = int(cfg.get("mp_degree", 1))
        self.sp_degree = int(cfg.get("sp_degree", 1))

        devices = devices if devices is not None else jax.devices()
        need = (self.dp_degree * self.pp_degree * self.sharding_degree *
                self.mp_degree * self.sp_degree)
        if need > len(devices):
            raise ValueError(f"hybrid config needs {need} devices, have {len(devices)}")
        devices = devices[:need]

        self._axis_names = ["dp", "pp", "sharding", "mp"]
        dims = [self.dp_degree, self.pp_degree, self.sharding_degree, self.mp_degree]
        if self.sp_degree > 1:
            self._axis_names.append("sp")
            dims.append(self.sp_degree)
        mesh_arr = np.asarray(devices).reshape(dims)
        self.mesh = Mesh(mesh_arr, tuple(self._axis_names))
        self.topology = CommunicateTopology(
            ("data", "pipe", "sharding", "model") + (("sep",) if self.sp_degree > 1 else ()),
            dims)
        self.global_rank = 0  # single-controller SPMD: rank-free programming model
        _GLOBAL_HCG[0] = self
        _GLOBAL_MESH[0] = self.mesh

    # ---- mesh access (TPU-native) ----
    def get_mesh(self) -> Mesh:
        return self.mesh

    # ---- paddle API parity ----
    def get_parallel_mode(self):
        if self.pp_degree > 1:
            return "pipeline"
        if self.sharding_degree > 1:
            return "sharding"
        if self.mp_degree > 1:
            return "tensor"
        return "data"

    def get_data_parallel_world_size(self):
        return self.dp_degree

    def get_model_parallel_world_size(self):
        return self.mp_degree

    def get_pipe_parallel_world_size(self):
        return self.pp_degree

    def get_sharding_parallel_world_size(self):
        return self.sharding_degree

    def get_sep_parallel_world_size(self):
        return self.sp_degree

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_model_parallel_group(self):
        return "mp"

    def get_data_parallel_group(self):
        return "dp"

    def get_pipe_parallel_group(self):
        return "pp"

    def get_sharding_parallel_group(self):
        return "sharding"

    def get_check_parallel_group(self):
        return None


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _GLOBAL_HCG[0]


def set_mesh(mesh: Mesh):
    _GLOBAL_MESH[0] = mesh


def get_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH[0]


class use_mesh:
    """Context manager scoping the active mesh (e.g. a pipeline stage's
    dp x mp submesh) so sharding constraints traced inside see the mesh the
    computation is actually jitted over, not the global hybrid mesh."""

    def __init__(self, mesh: Mesh):
        self._mesh = mesh

    def __enter__(self):
        self._prev = _GLOBAL_MESH[0]
        _GLOBAL_MESH[0] = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        _GLOBAL_MESH[0] = self._prev
        return False


def create_mesh(shape: Dict[str, int], devices=None) -> Mesh:
    """Direct mesh construction: create_mesh({'dp': 2, 'mp': 4})."""
    devices = devices if devices is not None else jax.devices()
    dims = list(shape.values())
    n = int(np.prod(dims))
    mesh = Mesh(np.asarray(devices[:n]).reshape(dims), tuple(shape.keys()))
    _GLOBAL_MESH[0] = mesh
    return mesh
