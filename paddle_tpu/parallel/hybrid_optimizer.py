"""HybridParallelOptimizer + group_sharded_parallel (ZeRO API).

Reference parity: `fleet/meta_parallel/dygraph_optimizer/
hybrid_parallel_optimizer.py:170` and
`python/paddle/distributed/sharding/group_sharded.py`
(group_sharded_parallel levels 'os' / 'os_g' / 'p_g_os' →
ShardingStage1/2/3 in `fleet/meta_parallel/sharding/`).

TPU-native: the optimizer wrapper builds an SPMDTrainStep on first use with
the right sharding stage; ZeRO levels map to PartitionSpecs on optimizer
state (os), gradients (os_g — XLA reduce-scatters into the sharded update),
and parameters (p_g_os).
"""
from __future__ import annotations

from typing import Optional

from .topology import get_hybrid_communicate_group


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss)


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """Stage-1 parity shell (`dygraph_sharding_optimizer.py:28`) — state
    sharding is applied by SPMDTrainStep(sharding_stage=1)."""

    def __init__(self, hcg=None, user_defined_strategy=None, params=None,
                 inner_optimizer_class=None, **inner_kw):
        if inner_optimizer_class is not None:
            inner = inner_optimizer_class(parameters=params, **inner_kw)
        else:
            inner = inner_kw.pop("inner_opt")
        super().__init__(inner, hcg, user_defined_strategy)
        self.sharding_stage = 1


def group_sharded_parallel(model, optimizer, level="os", scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False):
    """ZeRO levels: 'os' = stage1, 'os_g' = stage2, 'p_g_os' = stage3."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    opt = optimizer._inner_opt if isinstance(optimizer, HybridParallelOptimizer) \
        else optimizer
    wrapped = HybridParallelOptimizer(opt)
    wrapped.sharding_stage = stage
    model._sharding_stage = stage
    if scaler is not None:
        return model, wrapped, scaler
    return model, wrapped


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save
    save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
