"""paddle_tpu.parallel — the distributed stack (paddle.distributed parity).

Map (reference → TPU-native):
  NCCL rings / ProcessGroup     → mesh axes + XLA collectives (collective.py)
  topology.HybridCommunicateGroup → jax.sharding.Mesh (topology.py)
  dygraph Reducer DP            → batch sharding in the jitted step (data_parallel.py)
  imperative/reducer.cc buckets → backward-interleaved per-bucket allreduce
                                  (reducer.py, SPMDTrainStep grad_reduction="bucketed")
  mp_layers manual collectives  → GSPMD sharding annotations (mp_layers.py)
  PipelineParallel 1F1B + p2p   → per-stage submesh programs + device_put ICI hops
  Sharding stage 1/2/3 (ZeRO)   → PartitionSpecs on opt state/grads/params (spmd.py)
  — (absent in reference)       → ring attention + Ulysses SP (sp.py)
  fleet facade                  → fleet.py
  launch                        → launch.py (process per host)
"""
from .strategy import DistributedStrategy  # noqa: F401
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, create_mesh, get_mesh,
    get_hybrid_communicate_group,
)
from .collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, barrier, broadcast, get_group, irecv, isend, new_group,
    p2p_shift, recv, reduce, reduce_scatter, scatter, send, wait,
)
from .data_parallel import DataParallel  # noqa: F401
from .meta_parallel import ShardingParallel, TensorParallel  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .reducer import Reducer  # noqa: F401
from .spmd import SPMDTrainStep  # noqa: F401
from .sp import (  # noqa: F401
    SequenceParallelAttention, ring_attention_local, sequence_parallel_attention,
    ulysses_attention_local,
)
from .hybrid_optimizer import (  # noqa: F401
    DygraphShardingOptimizer, HybridParallelOptimizer, group_sharded_parallel,
    save_group_sharded_model,
)
from .moe import global_gather, global_scatter, moe_combine, moe_dispatch  # noqa: F401
from . import fleet  # noqa: F401

import os as _os


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn parity: single-controller TPU needs no spawn —
    run func directly (chips addressed via the mesh)."""
    func(*args)


def get_backend():
    return "xla"
