"""Pipeline layer segmentation.

Reference parity: `fleet/meta_parallel/parallel_layers/pp_layers.py:132,282`
(PipelineLayer with LayerDesc/SharedLayerDesc, seg_method segmentation).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ..nn.layer.container import Sequential
from ..nn.layer.layers import Layer


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Declares the full model as a flat list of LayerDescs, segmented into
    `num_stages` contiguous stages (uniform or param-weighted split)."""

    def __init__(self, layers: List, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None, num_virtual_pipeline_stages=1):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages or 1
        # interleaved (virtual-stage) pipeline: the model splits into
        # num_stages * V chunks; chain chunk c runs on physical stage
        # c % num_stages (reference pipeline_parallel.py:30 "1F1B +
        # interleave-able", Megatron virtual-pipeline assignment) — the
        # pipeline fills V times faster, shrinking the bubble fraction
        # from (P-1)/M toward (P-1)/(M*V)
        self.num_virtual_stages = max(int(num_virtual_pipeline_stages), 1)
        self.loss_fn = loss_fn
        self.seg_method = seg_method
        self._built_layers = []
        self._shared = {}
        for i, d in enumerate(self.descs):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
            elif isinstance(d, Layer):
                layer = d
            elif callable(d):
                layer = _FnLayer(d)
            else:
                raise TypeError(f"bad pipeline entry {d!r}")
            self._built_layers.append(layer)
            self.add_sublayer(str(i), layer)
        self._segment()

    def _segment(self):
        n = len(self._built_layers)
        k = self.num_stages * self.num_virtual_stages
        if self.seg_method.startswith("layer:"):
            # split at layers whose class name matches (reference seg_method)
            cls_name = self.seg_method.split(":", 1)[1]
            marks = [i for i, l in enumerate(self._built_layers)
                     if type(l).__name__ == cls_name]
            per = max(1, len(marks) // k)
            bounds = [0]
            for s in range(1, k):
                bounds.append(marks[min(s * per, len(marks) - 1)])
            bounds.append(n)
        else:
            # balanced split (i*n//k): slack spreads across chunks instead
            # of piling into (possibly empty) trailing ones — with virtual
            # stages k can approach n and the ceil split would starve the
            # tail chunks
            bounds = [i * n // k for i in range(k + 1)]
        self.segments = [(bounds[i], bounds[i + 1]) for i in range(k)]

    def get_stage_module(self, stage: int) -> Sequential:
        lo, hi = self.segments[stage]
        return Sequential(*self._built_layers[lo:hi])

    def get_stage_modules(self) -> List[Sequential]:
        """Chunks in CHAIN order (logical pipeline position); with virtual
        stages there are num_stages * V of them."""
        return [self.get_stage_module(s)
                for s in range(len(self.segments))]

    def chunk_to_stage(self, chunk: int) -> int:
        """Physical stage owning chain chunk `chunk`."""
        return chunk % self.num_stages

    def forward(self, x):
        for layer in self._built_layers:
            x = layer(x)
        return x


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)
