"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Reference parity: ABSENT in the reference snapshot (SURVEY.md §5 verified no
ring-attention/Ulysses/context-parallel) — this is the required new
first-class component, designed TPU-first:

  - Ring attention: K/V blocks rotate around the 'sp' mesh axis via
    `lax.ppermute` (ICI-neighbour hops make the ring free-standing), with
    flash-style online-softmax accumulation so memory stays O(block) and
    sequence length scales linearly with the number of chips.
  - Ulysses: `lax.all_to_all` swaps the sharded dimension seq→heads, runs
    dense attention on full sequence with H/sp heads per chip, then swaps
    back. Better for moderate sequence lengths with many heads.

Both run inside `shard_map` over the 'sp' axis; `sequence_parallel_attention`
wraps global arrays for direct use in models/tests.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.jaxcompat import axis_size as _axis_size
from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op
from .topology import get_mesh


def _block_attn(q, k, v, scale, mask=None):
    """One q-block × kv-block attention piece, returning (o_part, lse parts).

    q: [B,S,H,D]; returns m (running max logits), s (sumexp), o (weighted V).
    """
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.asarray(-1e9, logits.dtype))
    m = jnp.max(logits, axis=-1, keepdims=True)                  # [B,H,S,1]
    p = jnp.exp(logits - m)
    s = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhst,bthd->bshd", p, v)
    return m, s, o


def ring_attention_local(q, k, v, axis_name="sp", causal=False):
    """Per-shard ring attention (call inside shard_map).

    q/k/v: local shards [B, S_local, H, D]. Rotates K/V n-1 times via
    ppermute, accumulating with the online-softmax (flash) recurrence.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qpos = my * s_loc + jnp.arange(s_loc)

    m_acc = jnp.full((b, h, s_loc, 1), -jnp.inf, dtype=jnp.float32)
    s_acc = jnp.zeros((b, h, s_loc, 1), dtype=jnp.float32)
    o_acc = jnp.zeros((b, s_loc, h, d), dtype=jnp.float32)

    k_cur, v_cur = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        kb = (my - step) % n  # which block of K/V we currently hold
        if causal:
            kpos = kb * s_loc + jnp.arange(s_loc)
            mask = kpos[None, :] <= qpos[:, None]          # [S_loc, S_loc]
            mask = mask[None, None]                        # [1,1,S,S] → bhst
        else:
            mask = None
        m_new, s_new, o_new = _block_attn(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), scale, mask)
        m_tot = jnp.maximum(m_acc, m_new)
        alpha = jnp.exp(m_acc - m_tot)
        beta = jnp.exp(m_new - m_tot)
        s_acc = s_acc * alpha + s_new * beta
        o_acc = o_acc * jnp.moveaxis(alpha, 1, 2) + o_new * jnp.moveaxis(beta, 1, 2)
        m_acc = m_tot
        if step < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    out = o_acc / jnp.moveaxis(jnp.maximum(s_acc, 1e-20), 1, 2)
    return out.astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name="sp", causal=False):
    """Per-shard Ulysses attention (call inside shard_map).

    Swaps seq-sharded [B,S/n,H,D] → head-sharded [B,S,H/n,D] with all_to_all,
    runs dense (causal) attention over the FULL sequence, swaps back.
    """
    def seq2head(x):
        # split heads across the axis: [B,S/n,H,D] -> [B,S,H/n,D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    d = qh.shape[-1]
    scale = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bshd,bthd->bhst", qh.astype(jnp.float32),
                        kh.astype(jnp.float32)) * scale
    if causal:
        s_full = logits.shape[-2]
        cmask = jnp.tril(jnp.ones((s_full, s_full), dtype=bool))
        logits = jnp.where(cmask, logits, -1e9)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", p, vh.astype(jnp.float32))
    return head2seq(out.astype(q.dtype))


def sequence_parallel_attention(q, k, v, impl="ring", causal=False, mesh=None,
                                axis_name="sp"):
    """Global-array entry point: q/k/v [B, S, H, D] sharded (or shardable) on
    S over the 'sp' mesh axis. Differentiable (recorded as one tape node)."""
    mesh = mesh or get_mesh()
    if mesh is None or axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        from ..nn.functional.attention import scaled_dot_product_attention
        return scaled_dot_product_attention(q, k, v, is_causal=causal)
    q, k, v = ensure_tensor(q), ensure_tensor(k), ensure_tensor(v)
    if impl == "ring":
        # flash-block ring when the local shard can tile the MXU,
        # dense-block einsum ring otherwise (decided per-geometry inside)
        local = ring_flash_attention_local
    elif impl == "ring_dense":
        local = ring_attention_local
    else:
        local = ulysses_attention_local
    spec = P(None, axis_name, None, None)
    other = tuple(a for a in mesh.axis_names if a != axis_name)

    fn = shard_map(
        functools.partial(local, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)

    def f(qa, ka, va):
        ns = NamedSharding(mesh, spec)
        qa, ka, va = (lax.with_sharding_constraint(x, ns) if isinstance(x, jax.core.Tracer)
                      else jax.device_put(x, ns) for x in (qa, ka, va))
        return fn(qa, ka, va)

    return run_op(f, [q, k, v], f"{impl}_attention")


class SequenceParallelAttention:
    """Layer-ish wrapper selecting ring vs ulysses by sequence/head geometry."""

    def __init__(self, impl="auto", causal=True, axis_name="sp"):
        self.impl = impl
        self.causal = causal
        self.axis_name = axis_name

    def __call__(self, q, k, v):
        impl = self.impl
        if impl == "auto":
            mesh = get_mesh()
            n = mesh.shape.get(self.axis_name, 1) if mesh else 1
            heads = ensure_tensor(q).shape[2]
            impl = "ulysses" if heads % max(n, 1) == 0 and heads >= n * 2 else "ring"
        return sequence_parallel_attention(q, k, v, impl=impl, causal=self.causal,
                                           axis_name=self.axis_name)


# ---- ring attention with Pallas flash blocks -------------------------------

def _to_bhsd(x):
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, s, d), 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, block_q, block_k, interpret):
    out, _ = _ring_flash_fwd(q, k, v, axis_name, causal, block_q, block_k,
                             interpret)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, block_q, block_k, interpret):
    """Forward ring: flash kernel per hop, lse-weighted merge across hops.

    The per-hop kernel returns softmax-normalized block outputs plus their
    logsumexp; combining hops i with weights exp(lse_i - lse_total) is
    exactly the flash recurrence lifted to hop granularity, so the merged
    result equals full-sequence attention to numerical precision.
    """
    from ..kernels.flash_attention import ring_block_fwd
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    qf, kf, vf = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    o = jnp.zeros((b * h, s, d), jnp.float32)
    lse = jnp.full((b * h, 1, s), -1e30, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = kf, vf
    for step in range(n):
        kb = (my - step) % n
        offs = jnp.stack([my * s, kb * s]).astype(jnp.int32)
        o_b, lse_b = ring_block_fwd(qf, k_cur, v_cur, offs, causal=causal,
                                    block_q=block_q, block_k=block_k,
                                    interpret=interpret)
        lse_new = jnp.logaddexp(lse, lse_b)
        w_old = jnp.exp(lse - lse_new)
        w_new = jnp.exp(lse_b - lse_new)
        o = o * jnp.swapaxes(w_old, 1, 2) \
            + o_b.astype(jnp.float32) * jnp.swapaxes(w_new, 1, 2)
        lse = lse_new
        if step < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    out = _from_bhsd(o, b, h).astype(q.dtype)
    return out, lse


def _ring_flash_fwd_rule(q, k, v, axis_name, causal, block_q, block_k,
                         interpret):
    out, lse = _ring_flash_fwd(q, k, v, axis_name, causal, block_q, block_k,
                               interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd_rule(axis_name, causal, block_q, block_k, interpret, res,
                         g):
    """Backward ring: dq accumulates locally; dk/dv accumulators rotate WITH
    their k/v blocks and arrive home after the full ring (n hops). Uses the
    global lse, so per-hop probabilities are already globally normalized —
    hop contributions just sum (flash backward algebra, block-diagonal in
    hops)."""
    from ..kernels.flash_attention import ring_block_dq, ring_block_dkv
    q, k, v, out, lse = res
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    qf, kf, vf = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    of, dof = _to_bhsd(out), _to_bhsd(g)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)[:, None, :]
    dq = jnp.zeros((b * h, s, d), jnp.float32)
    dk_cur = jnp.zeros((b * h, s, d), jnp.float32)
    dv_cur = jnp.zeros((b * h, s, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = kf, vf
    for step in range(n):
        kb = (my - step) % n
        offs = jnp.stack([my * s, kb * s]).astype(jnp.int32)
        dq = dq + ring_block_dq(qf, k_cur, v_cur, dof, lse, delta, offs,
                                causal=causal, block_q=block_q,
                                block_k=block_k, interpret=interpret)
        dk_b, dv_b = ring_block_dkv(qf, k_cur, v_cur, dof, lse, delta, offs,
                                    causal=causal, block_q=block_q,
                                    block_k=block_k, interpret=interpret)
        dk_cur = dk_cur + dk_b
        dv_cur = dv_cur + dv_b
        # rotate grads WITH their k/v block; after n hops the grads are
        # home (k/v need not make the final hop — nothing reads them)
        if step < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
    dq_ = _from_bhsd(dq, b, h).astype(q.dtype)
    dk_ = _from_bhsd(dk_cur, b, h).astype(k.dtype)
    dv_ = _from_bhsd(dv_cur, b, h).astype(v.dtype)
    return dq_, dk_, dv_


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def ring_flash_attention_local(q, k, v, axis_name="sp", causal=False,
                               block_q=None, block_k=None):
    """Per-shard ring attention with Pallas flash block kernels (call inside
    shard_map). Falls back to the dense-block einsum ring when the local
    sequence is too short to tile the MXU."""
    from ..kernels.flash_attention import DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
    s_loc = q.shape[1]
    bq = min(block_q or DEFAULT_BLOCK_Q, s_loc)
    bk = min(block_k or DEFAULT_BLOCK_K, s_loc)
    if s_loc < 128 or s_loc % bq or s_loc % bk:
        return ring_attention_local(q, k, v, axis_name=axis_name,
                                    causal=causal)
    interpret = jax.default_backend() != "tpu"
    return _ring_flash(q, k, v, axis_name, causal, bq, bk, interpret)
