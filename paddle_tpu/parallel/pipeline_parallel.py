"""Pipeline-parallel engine — single-controller microbatch pipelining.

Reference parity: `fleet/meta_parallel/pipeline_parallel.py:30,152`
(PipelineParallel.train_batch, 1F1B `_forward_step:229`) + p2p via
`partial_send/recv` (`pp_utils/p2p_communication.py`).

TPU-native design: each stage owns a contiguous slice of chips, expressed as
a per-stage sub-`Mesh` (axes dp×mp inside the stage — the reference's
hybrid 4-D grid with the pp axis peeled off). Stage programs are pjit'ed on
their submesh; microbatch activations move stage→stage as device_put between
differently-placed arrays (ICI device-to-device DMA — the `send_v2/recv_v2`
replacement). The single controller enqueues work asynchronously, so stage
s can compute microbatch m while stage s+1 computes m-1: the 1F1B overlap
emerges from XLA's async dispatch rather than per-rank schedules.

Backward is rematerialized: each stage's backward recomputes its forward
from the saved stage INPUT (recompute-in-backward — the reference's
RecomputeOptimizer fused into the schedule), so activation memory is
O(microbatches × boundary) instead of O(all intermediates).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import random as rnd
from ..core.tensor import Tensor
from ..jit.functional import functional_call, split_state
from .pp_layers import PipelineLayer
from .topology import get_hybrid_communicate_group


class PipelineParallel:
    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        self.pipeline_layer = layers
        self.hcg = hcg or get_hybrid_communicate_group()
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.num_stages = layers.num_stages
        self.loss_fn = layers.loss_fn
        self.stages = layers.get_stage_modules()
        self._stage_meshes = self._make_stage_meshes()
        self._fwd_fns: List = [None] * self.num_stages
        self._bwd_fns: List = [None] * self.num_stages
        self._upd_fns: List = [None] * self.num_stages
        self._stage_state = []
        for s, mod in enumerate(self.stages):
            trainable, frozen = split_state(mod)
            self._stage_state.append((list(trainable), list(frozen)))
        self._placed = False
        self._opt_slots = None

    def _make_stage_meshes(self):
        if self.hcg is None:
            # single mesh over all devices, stages share devices (degenerate)
            devs = jax.devices()
            per = max(1, len(devs) // self.num_stages)
            return [Mesh(np.asarray(devs[s * per:(s + 1) * per]).reshape(-1, 1),
                         ("dp", "mp")) for s in range(self.num_stages)]
        mesh = self.hcg.get_mesh()
        arr = np.asarray(mesh.devices)  # [dp, pp, sharding, mp, (sp)]
        meshes = []
        for s in range(self.num_stages):
            sub = arr[:, s]  # [dp, sharding, mp, ...]
            sub = sub.reshape(arr.shape[0] * int(np.prod(sub.shape[1:-1] or [1])),
                              sub.shape[-1])
            meshes.append(Mesh(sub, ("dp", "mp")))
        return meshes

    # ---- per-stage compiled programs ----
    def _stage_fwd(self, s):
        if self._fwd_fns[s] is None:
            mod = self.stages[s]
            pnames, bnames = self._stage_state[s]
            mesh = self._stage_meshes[s]

            def f(params, buffers, x, key):
                rnd.push_trace_key(key)
                try:
                    return functional_call(mod, pnames, params, bnames, buffers, Tensor(x))
                finally:
                    rnd.pop_trace_key()

            batch_sh = NamedSharding(mesh, P("dp"))
            rep = NamedSharding(mesh, P())
            trainable, frozen = split_state(mod)
            psh = [NamedSharding(mesh, P(*(t.dist_attr or ())) if t.dist_attr else P())
                   for t in (trainable[n] for n in pnames)]
            self._fwd_fns[s] = jax.jit(
                f, in_shardings=(psh, [rep] * len(bnames), batch_sh, None),
                out_shardings=batch_sh)
        return self._fwd_fns[s]

    def _stage_bwd(self, s):
        if self._bwd_fns[s] is None:
            mod = self.stages[s]
            pnames, bnames = self._stage_state[s]
            mesh = self._stage_meshes[s]

            def b(params, buffers, x, g, key):
                rnd.push_trace_key(key)
                try:
                    def f2(ps, xx):
                        return functional_call(mod, pnames, ps, bnames, buffers,
                                               Tensor(xx))
                    _, vjp = jax.vjp(f2, params, x)
                    gp, gx = vjp(g)
                    return gp, gx
                finally:
                    rnd.pop_trace_key()

            self._bwd_fns[s] = jax.jit(b)
        return self._bwd_fns[s]

    def _loss_grad(self, out, labels):
        def lf(o, lab):
            loss = self.loss_fn(Tensor(o), *[Tensor(l) for l in lab])
            return loss._value if isinstance(loss, Tensor) else loss

        if not hasattr(self, "_loss_fn_jit"):
            self._loss_fn_jit = jax.jit(jax.value_and_grad(lf))
        return self._loss_fn_jit(out, labels)

    def _place_stage_params(self):
        for s, mod in enumerate(self.stages):
            mesh = self._stage_meshes[s]
            trainable, frozen = split_state(mod)
            pnames, bnames = self._stage_state[s]
            for n in pnames:
                t = trainable[n]
                spec = P(*t.dist_attr) if t.dist_attr else P()
                t._value = jax.device_put(t._value, NamedSharding(mesh, spec))
            for n in bnames:
                b = frozen[n]
                b._value = jax.device_put(b._value, NamedSharding(mesh, P()))
        self._placed = True

    # ---- the schedule ----
    def forward_backward_pipeline(self, data, labels):
        """GPipe-with-remat schedule; returns (mean_loss, stage_grads)."""
        if not self._placed:
            self._place_stage_params()
        n_micro = self.accumulate_steps
        micro_x = jnp.split(data, n_micro, axis=0)
        micro_y = [jnp.split(l, n_micro, axis=0) for l in labels]

        stage_params = []
        stage_buffers = []
        for s, mod in enumerate(self.stages):
            trainable, frozen = split_state(mod)
            pnames, bnames = self._stage_state[s]
            stage_params.append([trainable[n]._value for n in pnames])
            stage_buffers.append([frozen[n]._value for n in bnames])

        # forward: stream each microbatch through the stage chain (async dispatch
        # lets stage s work on micro m while stage s+1 handles m-1)
        keys = [[rnd.default_generator().next_key() for _ in range(self.num_stages)]
                for _ in range(n_micro)]
        boundary_inputs = [[None] * self.num_stages for _ in range(n_micro)]
        outs = [None] * n_micro
        for m in range(n_micro):
            x = micro_x[m]
            for s in range(self.num_stages):
                mesh = self._stage_meshes[s]
                x = jax.device_put(x, NamedSharding(mesh, P("dp")))  # ICI p2p hop
                boundary_inputs[m][s] = x
                x = self._stage_fwd(s)(stage_params[s], stage_buffers[s], x, keys[m][s])
            outs[m] = x

        # loss + backward per microbatch, reverse stage order
        grads = [None] * self.num_stages
        losses = []
        for m in range(n_micro):
            lab = [y[m] for y in micro_y]
            loss, g = self._loss_grad(outs[m], lab)
            losses.append(loss)
            for s in reversed(range(self.num_stages)):
                mesh = self._stage_meshes[s]
                g = jax.device_put(g, NamedSharding(mesh, P("dp")))
                gp, g = self._stage_bwd(s)(stage_params[s], stage_buffers[s],
                                           boundary_inputs[m][s], g, keys[m][s])
                if grads[s] is None:
                    grads[s] = gp
                else:
                    grads[s] = [a + b for a, b in zip(grads[s], gp)]
        scale = 1.0 / n_micro
        grads = [[g * scale for g in gs] for gs in grads]
        mean_loss = sum(jnp.mean(l) for l in losses) / n_micro
        return mean_loss, grads

    def train_batch(self, data, optimizer=None, lr_scheduler=None, scaler=None):
        if isinstance(data, (list, tuple)):
            x = data[0]._value if isinstance(data[0], Tensor) else jnp.asarray(data[0])
            labels = [d._value if isinstance(d, Tensor) else jnp.asarray(d)
                      for d in data[1:]]
        else:
            x, labels = (data._value if isinstance(data, Tensor) else jnp.asarray(data)), []
        loss, grads = self.forward_backward_pipeline(x, labels)

        if optimizer is not None:
            if self._opt_slots is None:
                self._opt_slots = []
                for s, mod in enumerate(self.stages):
                    trainable, _ = split_state(mod)
                    pts = [trainable[n] for n in self._stage_state[s][0]]
                    self._opt_slots.append(optimizer.init_state(pts))
            lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
            t = jnp.asarray(optimizer._step_count + 1, jnp.float32)
            for s, mod in enumerate(self.stages):
                trainable, _ = split_state(mod)
                pnames = self._stage_state[s][0]
                vals = [trainable[n]._value for n in pnames]
                if self._upd_fns[s] is None:
                    opt = optimizer

                    def upd(values, gs, slots, lr_, t_):
                        return opt.functional_update(values, gs, slots, lr_, t_)

                    self._upd_fns[s] = jax.jit(upd, donate_argnums=(0, 2))
                new_vals, self._opt_slots[s] = self._upd_fns[s](
                    vals, grads[s], self._opt_slots[s], lr, t)
                for n, v in zip(pnames, new_vals):
                    trainable[n]._value = v
            optimizer._step_count += 1
        return Tensor(loss)

    def eval_batch(self, data, compute_loss=True):
        if isinstance(data, (list, tuple)):
            x = data[0]._value if isinstance(data[0], Tensor) else jnp.asarray(data[0])
            labels = [d._value if isinstance(d, Tensor) else jnp.asarray(d)
                      for d in data[1:]]
        else:
            x, labels = jnp.asarray(data), []
        if not self._placed:
            self._place_stage_params()
        stage_params, stage_buffers = [], []
        for s, mod in enumerate(self.stages):
            trainable, frozen = split_state(mod)
            pnames, bnames = self._stage_state[s]
            stage_params.append([trainable[n]._value for n in pnames])
            stage_buffers.append([frozen[n]._value for n in bnames])
        key = rnd.default_generator().next_key()
        for s in range(self.num_stages):
            mesh = self._stage_meshes[s]
            x = jax.device_put(x, NamedSharding(mesh, P("dp")))
            x = self._stage_fwd(s)(stage_params[s], stage_buffers[s], x, key)
        if compute_loss and self.loss_fn is not None and labels:
            loss = self.loss_fn(Tensor(x), *[Tensor(l) for l in labels])
            return loss
        return Tensor(x)
