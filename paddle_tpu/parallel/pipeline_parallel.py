"""Pipeline-parallel engine — single-controller 1F1B microbatch pipelining.

Reference parity: `fleet/meta_parallel/pipeline_parallel.py:30,152`
(PipelineParallel.train_batch, 1F1B `_forward_step:229`) + p2p via
`partial_send/recv` (`pp_utils/p2p_communication.py`).

TPU-native design: each stage owns a contiguous slice of chips, expressed as
a per-stage sub-`Mesh` (axes dp×mp inside the stage — the reference's
hybrid 4-D grid with the pp axis peeled off). Stage programs are pjit'ed on
their submesh; microbatch activations move stage→stage as device_put between
differently-placed arrays (ICI device-to-device DMA — the `send_v2/recv_v2`
replacement).

Schedule: real 1F1B. Each stage follows the classic per-rank sequence —
warmup = min(n_micro, num_stages - stage - 1) forwards, then alternating
forward/backward in steady state, then a backward drain
(reference `pipeline_parallel.py:152`'s startup/steady/cooldown loops). The
single controller merges the per-stage sequences with a dependency-driven
worklist, so stage s's next op is enqueued the moment its input activation
(forward) or output-gradient (backward) exists; XLA's async dispatch runs
enqueued work on different stage meshes concurrently. In-flight saved
activations per LOGICAL stage are bounded by its warmup depth + 1 <= the
chain length (`last_peak_inflight` exposes the measured peak), unlike
GPipe's n_micro; under interleaving a physical device hosts V chunks, so
budget V x the per-chunk bound per device (the chunks are 1/V the size).

Interleaved (virtual-stage) schedule: PipelineLayer with
num_virtual_pipeline_stages=V splits the model into P*V chunks, chain
chunk c running on physical stage c % P — the pipeline fills V times
faster, so the bubble fraction drops from (P-1)/M toward (P-1)/(M*V)
(reference pipeline_parallel.py:30 "1F1B + interleave-able").

Backward is rematerialized: each stage's backward recomputes its forward
from the saved stage INPUT (recompute-in-backward — the reference's
RecomputeOptimizer fused into the schedule), so activation memory is
O(stages × boundary) instead of O(all intermediates).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import random as rnd
from ..core.tensor import Tensor
from ..jit.functional import functional_call, split_state
from .pp_layers import PipelineLayer
from .topology import get_hybrid_communicate_group


class PipelineParallel:
    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        self.pipeline_layer = layers
        self.hcg = hcg or get_hybrid_communicate_group()
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        # logical chain = physical stages x virtual chunks (interleaved
        # schedule); chunk l runs on physical mesh l % num_phys_stages
        self.num_phys_stages = layers.num_stages
        self.vpp = getattr(layers, "num_virtual_stages", 1)
        self.num_stages = layers.num_stages * self.vpp
        self.loss_fn = layers.loss_fn
        self.stages = layers.get_stage_modules()
        phys = self._make_stage_meshes()
        self._stage_meshes = [phys[layers.chunk_to_stage(l)]
                              for l in range(self.num_stages)]
        self._fwd_fns: List = [None] * self.num_stages
        self._bwd_fns: List = [None] * self.num_stages
        self._upd_fns: dict = {}
        self._stage_state = []
        for s, mod in enumerate(self.stages):
            trainable, frozen = split_state(mod)
            self._stage_state.append((list(trainable), list(frozen)))
        self._placed = False
        self._opt_slots = None

    def _make_stage_meshes(self):
        """One submesh per PHYSICAL stage (virtual chunks share theirs)."""
        P_ = self.num_phys_stages
        if self.hcg is None:
            # single mesh over all devices, stages share devices (degenerate)
            devs = jax.devices()
            per = max(1, len(devs) // P_)
            return [Mesh(np.asarray(devs[s * per:(s + 1) * per]).reshape(-1, 1),
                         ("dp", "mp")) for s in range(P_)]
        mesh = self.hcg.get_mesh()
        arr = np.asarray(mesh.devices)  # [dp, pp, sharding, mp, (sp)]
        meshes = []
        for s in range(P_):
            sub = arr[:, s]  # [dp, sharding, mp, ...]
            sub = sub.reshape(arr.shape[0] * int(np.prod(sub.shape[1:-1] or [1])),
                              sub.shape[-1])
            meshes.append(Mesh(sub, ("dp", "mp")))
        return meshes

    # ---- per-stage compiled programs ----
    def _stage_fwd(self, s):
        if self._fwd_fns[s] is None:
            mod = self.stages[s]
            pnames, bnames = self._stage_state[s]
            mesh = self._stage_meshes[s]

            def f(params, buffers, x, key):
                from .topology import use_mesh
                rnd.push_trace_key(key)
                try:
                    # trace under the STAGE submesh so mp sharding
                    # constraints bind to the stage's own dp x mp axes
                    with use_mesh(mesh):
                        return functional_call(mod, pnames, params, bnames,
                                               buffers, Tensor(x))
                finally:
                    rnd.pop_trace_key()

            batch_sh = NamedSharding(mesh, P("dp"))
            rep = NamedSharding(mesh, P())
            trainable, frozen = split_state(mod)
            psh = [NamedSharding(mesh, P(*(t.dist_attr or ())) if t.dist_attr else P())
                   for t in (trainable[n] for n in pnames)]
            self._fwd_fns[s] = jax.jit(
                f, in_shardings=(psh, [rep] * len(bnames), batch_sh, None),
                out_shardings=batch_sh)
        return self._fwd_fns[s]

    def _stage_bwd(self, s):
        if self._bwd_fns[s] is None:
            mod = self.stages[s]
            pnames, bnames = self._stage_state[s]
            mesh = self._stage_meshes[s]

            def b(params, buffers, x, g, key):
                from .topology import use_mesh
                rnd.push_trace_key(key)
                try:
                    def f2(ps, xx):
                        return functional_call(mod, pnames, ps, bnames, buffers,
                                               Tensor(xx))
                    with use_mesh(mesh):
                        _, vjp = jax.vjp(f2, params, x)
                        gp, gx = vjp(g)
                    return gp, gx
                finally:
                    rnd.pop_trace_key()

            self._bwd_fns[s] = jax.jit(b)
        return self._bwd_fns[s]

    def _loss_grad(self, out, labels):
        def lf(o, lab):
            loss = self.loss_fn(Tensor(o), *[Tensor(l) for l in lab])
            return loss._value if isinstance(loss, Tensor) else loss

        if not hasattr(self, "_loss_fn_jit"):
            self._loss_fn_jit = jax.jit(jax.value_and_grad(lf))
        return self._loss_fn_jit(out, labels)

    def _place_stage_params(self):
        for s, mod in enumerate(self.stages):
            mesh = self._stage_meshes[s]
            trainable, frozen = split_state(mod)
            pnames, bnames = self._stage_state[s]
            for n in pnames:
                t = trainable[n]
                spec = P(*t.dist_attr) if t.dist_attr else P()
                t._value = jax.device_put(t._value, NamedSharding(mesh, spec))
            for n in bnames:
                b = frozen[n]
                b._value = jax.device_put(b._value, NamedSharding(mesh, P()))
        self._placed = True

    # ---- the schedule ----
    def forward_backward_pipeline(self, data, labels):
        """1F1B schedule with remat backward; returns (mean_loss, stage_grads)."""
        if not self._placed:
            self._place_stage_params()
        S, M = self.num_stages, self.accumulate_steps
        micro_x = jnp.split(data, M, axis=0)
        micro_y = [jnp.split(l, M, axis=0) for l in labels]

        stage_params, stage_buffers = [], []
        for s, mod in enumerate(self.stages):
            trainable, frozen = split_state(mod)
            pnames, bnames = self._stage_state[s]
            stage_params.append([trainable[n]._value for n in pnames])
            stage_buffers.append([frozen[n]._value for n in bnames])

        keys = [[rnd.default_generator().next_key() for _ in range(S)]
                for _ in range(M)]

        # Per-stage 1F1B op sequence (reference pipeline_parallel.py:152):
        # warmup forwards, steady-state F/B pairs, backward drain.
        seqs = []
        for s in range(S):
            warm = min(M, S - s - 1)
            seq = ["F"] * warm
            for _ in range(M - warm):
                seq += ["F", "B"]
            seq += ["B"] * warm
            seqs.append(seq)

        ptr = [0] * S          # position in each stage's sequence
        fcnt = [0] * S         # next microbatch to forward, per stage
        bcnt = [0] * S         # next microbatch to backward, per stage
        acts = [dict() for _ in range(S)]   # acts[s][m]: input ready for fwd
        gin = [dict() for _ in range(S)]    # gin[s][m]: out-grad ready for bwd
        saved = [dict() for _ in range(S)]  # boundary inputs awaiting backward
        grads = [None] * S
        losses = [None] * M
        for m in range(M):
            acts[0][m] = micro_x[m]
        peak = 0
        remaining = 2 * S * M

        while remaining:
            progressed = False
            for s in range(S):
                if ptr[s] >= len(seqs[s]):
                    continue
                mesh = self._stage_meshes[s]
                if seqs[s][ptr[s]] == "F":
                    m = fcnt[s]
                    if m not in acts[s]:
                        continue  # upstream activation not produced yet
                    x = jax.device_put(acts[s].pop(m),
                                       NamedSharding(mesh, P("dp")))  # ICI hop
                    saved[s][m] = x
                    out = self._stage_fwd(s)(stage_params[s], stage_buffers[s],
                                             x, keys[m][s])
                    if s == S - 1:
                        lab = [y[m] for y in micro_y]
                        loss, g = self._loss_grad(out, lab)
                        losses[m] = loss
                        gin[s][m] = g
                    else:
                        acts[s + 1][m] = out
                    fcnt[s] += 1
                else:
                    m = bcnt[s]
                    if m not in gin[s]:
                        continue  # downstream gradient not produced yet
                    g = jax.device_put(gin[s].pop(m),
                                       NamedSharding(mesh, P("dp")))
                    gp, gx = self._stage_bwd(s)(stage_params[s], stage_buffers[s],
                                                saved[s].pop(m), g, keys[m][s])
                    grads[s] = gp if grads[s] is None else \
                        [a + b for a, b in zip(grads[s], gp)]
                    if s > 0:
                        gin[s - 1][m] = gx
                    bcnt[s] += 1
                ptr[s] += 1
                remaining -= 1
                progressed = True
                peak = max(peak, max(len(d) for d in saved))
            if not progressed:
                raise RuntimeError("pipeline schedule deadlock (bug)")

        self.last_peak_inflight = peak  # <= num_stages by construction
        scale = 1.0 / M
        grads = [[g * scale for g in gs] for gs in grads]
        mean_loss = sum(jnp.mean(l) for l in losses) / M
        return mean_loss, grads

    def train_batch(self, data, optimizer=None, lr_scheduler=None, scaler=None):
        if isinstance(data, (list, tuple)):
            x = data[0]._value if isinstance(data[0], Tensor) else jnp.asarray(data[0])
            labels = [d._value if isinstance(d, Tensor) else jnp.asarray(d)
                      for d in data[1:]]
        else:
            x, labels = (data._value if isinstance(data, Tensor) else jnp.asarray(data)), []
        loss, grads = self.forward_backward_pipeline(x, labels)

        if optimizer is not None:
            if self._opt_slots is None:
                self._opt_slots = []
                for s, mod in enumerate(self.stages):
                    trainable, _ = split_state(mod)
                    pts = [trainable[n] for n in self._stage_state[s][0]]
                    self._opt_slots.append(optimizer.init_state(pts))
            lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
            t = jnp.asarray(optimizer._step_count + 1, jnp.float32)

            stage_ptensors = []
            for s, mod in enumerate(self.stages):
                trainable, _ = split_state(mod)
                stage_ptensors.append([trainable[n]
                                       for n in self._stage_state[s][0]])

            # ClipGradByGlobalNorm must see the norm over ALL stages' params,
            # not per-stage: pre-scale grads by the global factor here and
            # disable in-update clipping below.
            from ..nn.clip import ClipGradByGlobalNorm
            clip = getattr(optimizer, "_grad_clip", None)
            clip_arg = "default"
            if isinstance(clip, ClipGradByGlobalNorm):
                clip_arg = None
                sq = 0.0
                for s in range(self.num_stages):
                    parts = [jnp.sum(g.astype(jnp.float32) ** 2)
                             for p, g in zip(stage_ptensors[s], grads[s])
                             if getattr(p, "need_clip", True)]
                    if parts:  # one reduce + one host sync per STAGE
                        sq += float(sum(parts))
                gn = sq ** 0.5
                factor = clip.clip_norm / max(gn, clip.clip_norm)
                if factor < 1.0:
                    for s in range(self.num_stages):
                        grads[s] = [g * jnp.asarray(factor, g.dtype)
                                    if getattr(p, "need_clip", True) else g
                                    for p, g in zip(stage_ptensors[s], grads[s])]

            for s in range(self.num_stages):
                pts = stage_ptensors[s]
                vals = [p._value for p in pts]
                # clip_arg is part of the cache key: grad_clip set/changed
                # after the first step must not reuse a stale closure.
                fkey = (s, None if clip_arg is None else type(clip).__name__)
                if fkey not in self._upd_fns:
                    opt = optimizer

                    def upd(values, gs, slots, lr_, t_, _pts=pts, _clip=clip_arg):
                        return opt.functional_update(values, gs, slots, lr_, t_,
                                                     params_meta=_pts,
                                                     grad_clip=_clip)

                    self._upd_fns[fkey] = jax.jit(upd, donate_argnums=(0, 2))
                new_vals, self._opt_slots[s] = self._upd_fns[fkey](
                    vals, grads[s], self._opt_slots[s], lr, t)
                for p, v in zip(pts, new_vals):
                    p._value = v
            optimizer._step_count += 1
        return Tensor(loss)

    def eval_batch(self, data, compute_loss=True):
        if isinstance(data, (list, tuple)):
            x = data[0]._value if isinstance(data[0], Tensor) else jnp.asarray(data[0])
            labels = [d._value if isinstance(d, Tensor) else jnp.asarray(d)
                      for d in data[1:]]
        else:
            x, labels = jnp.asarray(data), []
        if not self._placed:
            self._place_stage_params()
        stage_params, stage_buffers = [], []
        for s, mod in enumerate(self.stages):
            trainable, frozen = split_state(mod)
            pnames, bnames = self._stage_state[s]
            stage_params.append([trainable[n]._value for n in pnames])
            stage_buffers.append([frozen[n]._value for n in bnames])
        key = rnd.default_generator().next_key()
        for s in range(self.num_stages):
            mesh = self._stage_meshes[s]
            x = jax.device_put(x, NamedSharding(mesh, P("dp")))
            x = self._stage_fwd(s)(stage_params[s], stage_buffers[s], x, key)
        if compute_loss and self.loss_fn is not None and labels:
            loss = self.loss_fn(Tensor(x), *[Tensor(l) for l in labels])
            return loss
        return Tensor(x)
