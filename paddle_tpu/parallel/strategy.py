"""DistributedStrategy — the fleet configuration object.

Reference parity: `paddle/fluid/framework/distributed_strategy.proto:271-331`
(~37 toggles) + `python/paddle/distributed/fleet/base/distributed_strategy.py:109`.
Toggles that are GPU-era no-ops on TPU (nccl_comm_num, …) are accepted and
recorded so reference configs load unchanged.
"""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        # hybrid parallelism degrees (topology.py consumes these)
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sp_degree": 1,  # TPU-new: sequence/context parallel axis
        }
        # amp
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_bf16": True,
                            "custom_white_list": [], "custom_black_list": []}
        # recompute
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        # sharding (static-style config)
        self.sharding = False
        self.sharding_configs = {"stage": 1, "sharding_degree": 1}
        # pipeline
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1,
                                 "schedule_mode": "1F1B"}
        # tensor parallel
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        # gradient merge
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        # misc parity toggles (recorded, mapped or no-op on TPU)
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "sparsity": 0.999}
        self.lamb = False
        self.lars = False
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 4}
        self.a_sync = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = False
        self.fp16_allreduce = False
        self.last_comm_group_size_MB = 1
        self.without_graph_optimization = False
        self.asp = False

    def to_dict(self):
        return dict(self.__dict__)

    def __repr__(self):
        on = [k for k, v in self.__dict__.items() if v is True]
        return f"DistributedStrategy(hybrid={self.hybrid_configs}, enabled={on})"
