"""Fleet facade.

Reference parity: `python/paddle/distributed/fleet/base/fleet_base.py:170
(init), 896 (distributed_model), 839 (distributed_optimizer)` + role maker.
TPU-native: init builds the HybridCommunicateGroup mesh; distributed_model
returns the right engine wrapper (DataParallel / TensorParallel /
PipelineParallel); distributed_optimizer returns a HybridParallelOptimizer
whose step() routes through the SPMD machinery.
"""
from __future__ import annotations

import os
from typing import Optional

from .env import get_rank, get_world_size, init_parallel_env
from .strategy import DistributedStrategy
from .topology import HybridCommunicateGroup, get_hybrid_communicate_group

_FLEET = {"init": False, "strategy": None, "hcg": None}


class PaddleCloudRoleMaker:
    """Env-var role discovery (`fleet/base/role_maker.py:519`)."""

    def __init__(self, is_collective=True, **kwargs):
        self.is_collective = is_collective

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_worker(self):
        return True


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective)


def init(role_maker=None, is_collective=True, strategy=None, log_dir=None):
    strategy = strategy or DistributedStrategy()
    init_parallel_env()
    hcg = HybridCommunicateGroup(strategy)
    _FLEET.update(init=True, strategy=strategy, hcg=hcg)
    return hcg


def is_first_worker():
    return get_rank() == 0


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def get_hybrid_group() -> Optional[HybridCommunicateGroup]:
    return _FLEET["hcg"] or get_hybrid_communicate_group()


def get_strategy() -> Optional[DistributedStrategy]:
    return _FLEET["strategy"]


def distributed_model(model):
    """Wrap by hybrid config (`fleet_base.py:956-990`)."""
    from .data_parallel import DataParallel
    from .pipeline_parallel import PipelineParallel
    from .pp_layers import PipelineLayer

    hcg = get_hybrid_group()
    strategy = _FLEET["strategy"] or DistributedStrategy()
    if hcg is not None and hcg.pp_degree > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError("pp_degree>1 requires a PipelineLayer model")
        model.num_stages = hcg.pp_degree
        model._segment()
        return PipelineParallel(model, hcg, strategy)
    if hcg is not None and (hcg.mp_degree > 1 or hcg.sharding_degree > 1):
        from .meta_parallel import TensorParallel
        return TensorParallel(model, hcg, strategy)
    return DataParallel(model, hcg=hcg, strategy=strategy)


def distributed_optimizer(optimizer, strategy=None):
    from .hybrid_optimizer import HybridParallelOptimizer
    return HybridParallelOptimizer(optimizer, get_hybrid_group(),
                                   strategy or _FLEET["strategy"])


# PS-mode surface (reference fleet PS API) — not in the TPU round-1 scope;
# explicit errors keep ports honest.
def init_server(*a, **kw):
    raise NotImplementedError("parameter-server mode: planned (CTR tier, round 2+)")


def init_worker(*a, **kw):
    raise NotImplementedError("parameter-server mode: planned (CTR tier, round 2+)")


def run_server():
    raise NotImplementedError("parameter-server mode: planned (CTR tier, round 2+)")


def stop_worker():
    pass


def barrier_worker():
    from .collective import barrier
    barrier()


def save_inference_model(*a, **kw):
    raise NotImplementedError("use paddle_tpu.jit.save")


def save_persistables(executor=None, dirname=None, main_program=None, **kw):
    raise NotImplementedError("use paddle_tpu.save(model.state_dict(), path)")
