"""Fleet facade.

Reference parity: `python/paddle/distributed/fleet/base/fleet_base.py:170
(init), 896 (distributed_model), 839 (distributed_optimizer)` + role maker.
TPU-native: init builds the HybridCommunicateGroup mesh; distributed_model
returns the right engine wrapper (DataParallel / TensorParallel /
PipelineParallel); distributed_optimizer returns a HybridParallelOptimizer
whose step() routes through the SPMD machinery.
"""
from __future__ import annotations

import os
from typing import Optional

from .env import get_rank, get_world_size, init_parallel_env
from .strategy import DistributedStrategy
from .topology import HybridCommunicateGroup, get_hybrid_communicate_group

_FLEET = {"init": False, "strategy": None, "hcg": None}


class PaddleCloudRoleMaker:
    """Env-var role discovery (`fleet/base/role_maker.py:519`)."""

    def __init__(self, is_collective=True, **kwargs):
        self.is_collective = is_collective

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_worker(self):
        return True


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective)


def init(role_maker=None, is_collective=True, strategy=None, log_dir=None):
    strategy = strategy or DistributedStrategy()
    init_parallel_env()
    hcg = HybridCommunicateGroup(strategy)
    _FLEET.update(init=True, strategy=strategy, hcg=hcg)
    return hcg


def is_first_worker():
    return get_rank() == 0


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def get_hybrid_group() -> Optional[HybridCommunicateGroup]:
    return _FLEET["hcg"] or get_hybrid_communicate_group()


def get_strategy() -> Optional[DistributedStrategy]:
    return _FLEET["strategy"]


def distributed_model(model):
    """Wrap by hybrid config (`fleet_base.py:956-990`)."""
    from .data_parallel import DataParallel
    from .pipeline_parallel import PipelineParallel
    from .pp_layers import PipelineLayer

    hcg = get_hybrid_group()
    strategy = _FLEET["strategy"] or DistributedStrategy()
    if hcg is not None and hcg.pp_degree > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError("pp_degree>1 requires a PipelineLayer model")
        model.num_stages = hcg.pp_degree
        model._segment()
        return PipelineParallel(model, hcg, strategy)
    if hcg is not None and (hcg.mp_degree > 1 or hcg.sharding_degree > 1):
        from .meta_parallel import TensorParallel
        return TensorParallel(model, hcg, strategy)
    return DataParallel(model, hcg=hcg, strategy=strategy)


def distributed_optimizer(optimizer, strategy=None):
    """Wrap per DistributedStrategy toggles (the meta-optimizer resolution
    the reference does in fleet_base.py:1367 `_minimize_impl`): gradient
    merge and LocalSGD stack around the hybrid optimizer; recompute is an
    API (`fleet.utils.recompute`) applied at model level."""
    from .hybrid_optimizer import HybridParallelOptimizer
    st = strategy or _FLEET["strategy"]
    dgc_inner_m = 0.0
    if st is not None and getattr(st, "dgc", False):
        # DGC lifts momentum out of the inner optimizer; work on a shallow
        # copy so the caller's object (and its state_dict) is untouched
        dgc_inner_m = float(getattr(optimizer, "_momentum", 0.0) or 0.0)
        if dgc_inner_m > 0:
            import copy
            optimizer = copy.copy(optimizer)
            optimizer._momentum = 0.0
            if hasattr(optimizer, "_jit_cache"):
                optimizer._jit_cache = {}
    opt = HybridParallelOptimizer(optimizer, get_hybrid_group(), st)
    if st is not None and getattr(st, "gradient_merge", False):
        from .meta_optimizers import GradientMergeOptimizer
        cfg = getattr(st, "gradient_merge_configs", {})
        opt = GradientMergeOptimizer(opt, k_steps=cfg.get("k_steps", 1),
                                     avg=cfg.get("avg", True))
    if st is not None and getattr(st, "localsgd", False):
        from .meta_optimizers import LocalSGDOptimizer
        cfg = getattr(st, "localsgd_configs", {"k_steps": 4})
        opt = LocalSGDOptimizer(opt, k_steps=cfg.get("k_steps", 4))
    if st is not None and getattr(st, "dgc", False):
        from .meta_optimizers import DGCMomentumOptimizer
        cfg = getattr(st, "dgc_configs", {})
        # reference usage is distributed_optimizer(Momentum(...)) with
        # dgc=True: lift the inner momentum into DGC (which IS the
        # momentum optimizer) so it isn't applied twice
        momentum = cfg.get("momentum")
        if momentum is None:
            momentum = dgc_inner_m if dgc_inner_m > 0 else 0.9
        opt = DGCMomentumOptimizer(
            opt, momentum=momentum, sparsity=cfg.get("sparsity", 0.999),
            rampup_begin_step=cfg.get("rampup_begin_step", 0))
    return opt


# PS-mode surface (reference fleet PS API, fleet_base.py init_server/
# init_worker/run_server/stop_worker) — backed by the PS tier
# (paddle_tpu.distributed.ps, reference ps/service/brpc_ps_*).
_PS_CTX = [None]


def _ps_context():
    if _PS_CTX[0] is None:
        from ..distributed.ps import PsContext
        _PS_CTX[0] = PsContext()
    return _PS_CTX[0]


def init_server(host="127.0.0.1", port=0, **kw):
    return _ps_context().init_server(host, port)


def init_worker(endpoints=None, **kw):
    return _ps_context().init_worker(endpoints=endpoints)


def run_server(block=True):
    return _ps_context().run_server(block=block)


def stop_worker():
    ctx = _PS_CTX[0]
    if ctx is not None:
        ctx.stop_worker()


def barrier_worker():
    from .collective import barrier
    barrier()


class _FleetUtils:
    """fleet.utils namespace (reference fleet/utils/: recompute etc.)."""

    @staticmethod
    def recompute(function, *args, **kwargs):
        from .meta_optimizers import recompute as _rc
        return _rc(function, *args, **kwargs)


utils = _FleetUtils()


def save_inference_model(*a, **kw):
    raise NotImplementedError("use paddle_tpu.jit.save")


def save_persistables(executor=None, dirname=None, main_program=None, **kw):
    raise NotImplementedError("use paddle_tpu.save(model.state_dict(), path)")
