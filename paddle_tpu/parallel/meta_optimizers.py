"""Meta-optimizers: recompute, gradient merge, LocalSGD.

Reference parity: `fleet/meta_optimizers/recompute_optimizer.py` (+
`fleet/utils/recompute` dygraph API), `gradient_merge_optimizer.py`
(accumulate k micro-steps then apply), `localsgd_optimizer.py` (local
steps + periodic parameter averaging). The reference implements these as
program rewrites; here they wrap the imperative tape/optimizer directly.

TPU-native recompute: the forward runs WITHOUT storing residuals (no tape
nodes inside); ONE tape node is recorded whose vjp re-runs the forward
under `jax.vjp` at backward time — activation memory traded for FLOPs,
the `jax.checkpoint` policy expressed at tape level (and `jax.checkpoint`
itself is applied when tracing inside jit).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core.tensor import Tensor
from ..jit.functional import functional_call, split_state


def recompute(function: Callable, *args, **kwargs):
    """fleet.utils.recompute parity: run `function` (a Layer or callable)
    without storing intermediate activations; recompute them in backward.

    Gradients flow to tensor args AND, when `function` is a Layer, to its
    parameters (functional substitution)."""
    from ..nn.layer.layers import Layer

    arg_tensors = [a for a in args if isinstance(a, Tensor)]
    if isinstance(function, Layer):
        trainable, frozen = split_state(function)
        pnames, bnames = list(trainable), list(frozen)
        ptensors = [trainable[n] for n in pnames]
        btensors = [frozen[n] for n in bnames]
        diff_inputs = arg_tensors + [p for p in ptensors if not p.stop_gradient]

        def pure(*arrs):
            n_args = len(arg_tensors)
            it = iter(arrs[:n_args])
            rebuilt = [Tensor(next(it)) if isinstance(a, Tensor) else a
                       for a in args]
            pvals = list(arrs[n_args:])
            # frozen/stop-gradient params enter as constants
            full = []
            k = 0
            for p in ptensors:
                if p.stop_gradient:
                    full.append(p._value)
                else:
                    full.append(pvals[k])
                    k += 1
            out = functional_call(function, pnames, full, bnames,
                                  [b._value for b in btensors],
                                  *rebuilt, **kwargs)
            return out._value if isinstance(out, Tensor) else out
    else:
        diff_inputs = arg_tensors

        def pure(*arrs):
            it = iter(arrs)
            rebuilt = [Tensor(next(it)) if isinstance(a, Tensor) else a
                       for a in args]
            out = function(*rebuilt, **kwargs)
            return out._value if isinstance(out, Tensor) else out

    arrays = tuple(t._value for t in diff_inputs)
    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        # already inside a jit trace: jax.checkpoint IS the recompute
        return Tensor(jax.checkpoint(pure)(*arrays))

    from ..core import random as rnd
    rng_before = rnd.get_rng_state()  # preserve_rng_state (reference
    # recompute replays the SAME dropout masks in the backward re-run)
    with autograd.no_grad():
        out_val = pure(*arrays)  # forward only: no residuals retained
    out = Tensor(out_val)
    if autograd.is_grad_enabled() and diff_inputs:

        def lazy_vjp(g):
            g = g._value if hasattr(g, "_value") else g
            cur = rnd.get_rng_state()
            rnd.set_rng_state(rng_before)
            try:
                _, vjp_fn = jax.vjp(pure, *arrays)  # re-run forward NOW
            finally:
                rnd.set_rng_state(cur)  # leave surrounding RNG untouched
            return vjp_fn(g)

        autograd.record_node(lazy_vjp, diff_inputs, [out], "recompute")
    return out


class GradientMergeOptimizer:
    """Accumulate gradients for k_steps micro-steps, then apply ONE inner
    optimizer step with the (averaged) merged grads
    (gradient_merge_optimizer.py / GradientMergeOptimizer)."""

    def __init__(self, inner_optimizer, k_steps: int = 1, avg: bool = True):
        self.inner_optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg
        self._acc = {}  # id(param) -> (param, accumulated grad)
        self._micro = 0

    def __getattr__(self, name):
        # delegate the rest of the optimizer API (state_dict, set_lr, ...)
        if name == "inner_optimizer":
            raise AttributeError(name)  # guard pre-__init__ recursion
        return getattr(self.inner_optimizer, name)

    def step(self):
        from ..core.selected_rows import SelectedRows
        params = [p for p in (self.inner_optimizer._parameter_list or [])
                  if not p.stop_gradient and p.grad is not None]
        for p in params:
            g = p.grad._value if isinstance(p.grad, Tensor) else p.grad
            if isinstance(g, SelectedRows):
                g = g.to_dense()
            cur = self._acc.get(id(p))
            self._acc[id(p)] = (p, g if cur is None else cur[1] + g)
        self._micro += 1
        if self._micro < self.k_steps:
            # merge-only step: inner optimizer must NOT run
            for p in params:
                p.grad = None
            return
        scale = 1.0 / self.k_steps if self.avg else 1.0
        # write back over EVERY accumulated param — including ones with no
        # grad on this final micro-step (conditional branches, unused params)
        for p, acc in self._acc.values():
            p.grad = acc * scale
        self.inner_optimizer.step()
        self._acc.clear()
        self._micro = 0

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self, *a, **kw):
        self.inner_optimizer.clear_grad(*a, **kw)


class LocalSGDOptimizer:
    """Local steps + periodic parameter averaging across the data-parallel
    group (localsgd_optimizer.py): every k_steps, params := mean over
    replicas. The averaging collective is injectable; by default it uses
    the eager collective all_reduce when a process group is initialized
    and is a no-op single-process."""

    def __init__(self, inner_optimizer, k_steps: int = 4,
                 allreduce_mean: Optional[Callable] = None):
        self.inner_optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self._steps = 0
        self._allreduce_mean = allreduce_mean

    def __getattr__(self, name):
        if name == "inner_optimizer":
            raise AttributeError(name)  # guard pre-__init__ recursion
        return getattr(self.inner_optimizer, name)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def _default_mean(self, arr):
        from . import collective
        from .env import get_world_size
        if get_world_size() <= 1:
            return arr
        t = Tensor(arr)
        collective.all_reduce(t)
        return t._value / get_world_size()

    def step(self):
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k_steps == 0:
            mean = self._allreduce_mean or self._default_mean
            for p in (self.inner_optimizer._parameter_list or []):
                p._value = jnp.asarray(mean(p._value))

    def clear_grad(self, *a, **kw):
        self.inner_optimizer.clear_grad(*a, **kw)


class DGCMomentumOptimizer:
    """Deep Gradient Compression (reference `operators/dgc_op.cc` +
    `details/sparse_all_reduce_op_handle.cc`, fleet dgc toggle): keep only
    the top-k% of gradient values per step; the rest ACCUMULATE locally
    (with momentum correction) until they grow large enough to send.

    TPU framing: the compressed "send" is the sparsified gradient handed to
    the wrapped optimizer (and, cross-process, to the injectable allreduce);
    locality = the residual buffers. rampup_begin_step delays compression
    (reference warmup).
    """

    def __init__(self, inner_optimizer, momentum: float = 0.9,
                 sparsity: float = 0.999, rampup_begin_step: int = 0,
                 allreduce: Optional[Callable] = None):
        # DGC IS the momentum optimizer (reference DGCMomentumOptimizer
        # subclasses Momentum): the inner applier must be momentum-free or
        # the velocity is applied twice and training diverges
        if float(getattr(inner_optimizer, "_momentum", 0.0)) > 0.0:
            raise ValueError(
                "DGCMomentumOptimizer provides momentum itself; wrap a "
                "momentum-free optimizer (e.g. SGD) and pass momentum= here")
        self.inner_optimizer = inner_optimizer
        self.momentum = float(momentum)
        # fraction DROPPED. The reference config format is a RAMP
        # (list[float], e.g. [0.75, 0.9375, 0.984, 0.996, 0.999]) applied
        # over post-warmup steps; a scalar means a constant ramp of one.
        if isinstance(sparsity, (list, tuple)):
            self._sparsity_ramp = [float(s) for s in sparsity] or [0.999]
        else:
            self._sparsity_ramp = [float(sparsity)]
        self.rampup_begin_step = int(rampup_begin_step)
        self._allreduce = allreduce
        self._u = {}  # momentum-corrected velocity per param
        self._v = {}  # local accumulation (residual) per param
        self._steps = 0

    def __getattr__(self, name):
        if name == "inner_optimizer":
            raise AttributeError(name)
        return getattr(self.inner_optimizer, name)

    def step(self):
        from ..core.selected_rows import SelectedRows
        self._steps += 1
        params = [p for p in (self.inner_optimizer._parameter_list or [])
                  if not p.stop_gradient and p.grad is not None]
        if self._steps <= self.rampup_begin_step:
            # warmup: dense allreduce of the RAW gradient + FULL momentum
            # update, no sparsification (reference DGCMomentumOptimizer is
            # a Momentum subclass and allreduces dense pre-rampup — ranks
            # must not desync during warmup)
            for p in params:
                g = p.grad._value if isinstance(p.grad, Tensor) else p.grad
                if isinstance(g, SelectedRows):
                    g = g.to_dense()
                g = jnp.asarray(g)
                if self._allreduce is not None:
                    g = jnp.asarray(self._allreduce(g))
                u = self._u.get(id(p))
                u = g if u is None else self.momentum * u + g
                self._u[id(p)] = u
                p.grad = u
            self.inner_optimizer.step()
            return
        for p in params:
            g = p.grad._value if isinstance(p.grad, Tensor) else p.grad
            if isinstance(g, SelectedRows):
                g = g.to_dense()
            g = jnp.asarray(g)
            u = self._u.get(id(p))
            v = self._v.get(id(p))
            u = g if u is None else self.momentum * u + g  # momentum corr.
            v = u if v is None else v + u                  # local accumulate
            flat = v.reshape(-1)
            ramp_i = min(self._steps - self.rampup_begin_step - 1,
                         len(self._sparsity_ramp) - 1)
            k = max(1, int(flat.size * (1.0 - self._sparsity_ramp[ramp_i])))
            thresh = jax.lax.top_k(jnp.abs(flat), k)[0][-1]
            mask = jnp.abs(v) >= thresh
            send = jnp.where(mask, v, 0.0)
            if self._allreduce is not None:
                send = jnp.asarray(self._allreduce(send))
            p.grad = send.astype(g.dtype)
            # masked-out values stay in the residual; sent values clear
            self._v[id(p)] = jnp.where(mask, 0.0, v)
            self._u[id(p)] = jnp.where(mask, 0.0, u)
        self.inner_optimizer.step()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self, *a, **kw):
        self.inner_optimizer.clear_grad(*a, **kw)

    def state_dict(self):
        """Inner state PLUS the residual/velocity buffers: with high
        sparsity those hold most recent gradient mass — dropping them on
        resume would change convergence (checkpoint parity)."""
        plist = self.inner_optimizer._parameter_list or []
        idx = {id(p): i for i, p in enumerate(plist)}
        import numpy as np
        return {"inner": self.inner_optimizer.state_dict(),
                "dgc_steps": self._steps,
                "dgc_u": {idx[k]: np.asarray(v) for k, v in self._u.items()
                          if k in idx},
                "dgc_v": {idx[k]: np.asarray(v) for k, v in self._v.items()
                          if k in idx}}

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd["inner"])
        self._steps = int(sd.get("dgc_steps", 0))
        plist = self.inner_optimizer._parameter_list or []
        self._u = {id(plist[int(i)]): jnp.asarray(v)
                   for i, v in sd.get("dgc_u", {}).items()}
        self._v = {id(plist[int(i)]): jnp.asarray(v)
                   for i, v in sd.get("dgc_v", {}).items()}
