"""MoE: top-k gating, capacity buckets, expert-parallel dispatch/combine.

Reference parity: `operators/collective/global_scatter_op.cc` /
`global_gather_op.cc` (count-driven token exchange), python wrappers
`distributed/utils.py:52-129`, and the incubate MoELayer gate semantics.

TPU-native redesign (GShard formulation): variable-count LoD exchange
becomes STATIC-shape capacity buckets — gating produces a dispatch mask
[T, E, C] and combine weights [T, E, C]; dispatch/combine are einsums (MXU
work, not gather loops); the cross-device hop is one `lax.all_to_all` over
the 'ep' mesh axis inside shard_map. Experts are evaluated as ONE batched
einsum over stacked weights [E_local, d, h] instead of a per-expert loop.
`local_count`/`global_count` survive as optional per-bucket validity counts
(rows beyond the count are masked), honoring the reference op contract
under static shapes.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.jaxcompat import axis_size as _axis_size
from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op
from .collective import _in_spmd


# ---------------- gating ----------------
def top_k_gating(logits, k=2, capacity=None, capacity_factor=1.25,
                 normalize=True):
    """GShard-style top-k gate.

    logits: [T, E]. Returns (dispatch [T,E,C] bool-as-float, combine
    [T,E,C] float, aux_loss scalar). Capacity defaults to
    ceil(capacity_factor * k * T / E). Tokens overflowing an expert's
    capacity are dropped (zero combine weight) — reference drop policy.
    """
    T, E = logits.shape
    if capacity is None:
        capacity = int(math.ceil(capacity_factor * k * T / E))
    C = int(capacity)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    remaining = probs
    offset = jnp.zeros((E,), jnp.int32)
    combine = jnp.zeros((T, E, C), jnp.float32)
    dispatch = jnp.zeros((T, E, C), jnp.float32)
    gates_sum = jnp.zeros((T,), jnp.float32)
    top1_mask = None
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                     # [T]
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)         # [T, E]
        if top1_mask is None:
            top1_mask = mask
        pos = (jnp.cumsum(mask, axis=0) - 1) * mask + offset[None, :] * mask
        pos_t = jnp.sum(pos, axis=-1).astype(jnp.int32)          # [T]
        keep = (jnp.sum(mask * (pos + 1), axis=-1) > 0) & (pos_t < C)
        gate = jnp.sum(probs * mask, axis=-1)                    # [T]
        sel = mask * keep[:, None]                               # [T, E]
        slot = jax.nn.one_hot(jnp.clip(pos_t, 0, C - 1), C,
                              dtype=jnp.float32)                 # [T, C]
        dispatch = dispatch + sel[:, :, None] * slot[:, None, :]
        combine = combine + (gate[:, None, None] * sel[:, :, None]
                             * slot[:, None, :])
        gates_sum = gates_sum + gate * keep
        offset = offset + jnp.sum(sel, axis=0).astype(jnp.int32)
        remaining = remaining * (1.0 - mask)

    if normalize and k > 1:
        combine = combine / jnp.maximum(gates_sum, 1e-9)[:, None, None]

    # load-balancing aux loss (Switch/GShard): E * sum_e mean_probs_e *
    # fraction_of_tokens_routed_e (top-1 routing fractions)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(top1_mask, axis=0)
    aux_loss = E * jnp.sum(me * ce)
    return dispatch, combine, aux_loss


def moe_dispatch(x, dispatch):
    """x: [T, d], dispatch: [T, E, C] -> expert inputs [E, C, d] (einsum)."""
    return jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32)
                      ).astype(x.dtype)


def moe_combine(expert_out, combine):
    """expert_out: [E, C, d], combine: [T, E, C] -> [T, d]."""
    return jnp.einsum("tec,ecd->td", combine,
                      expert_out.astype(jnp.float32)).astype(expert_out.dtype)


# ---------------- count-masked a2a (global_scatter/gather op contract) ----
def _mask_counts(a, count):
    """Zero bucket rows at index >= count. a: [E, C, d], count: [E]."""
    C = a.shape[1]
    valid = lax.broadcasted_iota(jnp.int32, (a.shape[0], C), 1) < count[:, None]
    return jnp.where(valid[:, :, None], a, jnp.zeros((), a.dtype))


def global_scatter(x, local_count=None, global_count=None, group=None):
    """Send bucketed expert inputs to their owning ranks.

    x: [E, C, d] grouped by destination expert (E = ep * E_local). Returns
    [E_local, ep*C, d] on each rank: this rank's experts' buckets from every
    source. `local_count[e]` (optional) marks how many rows of bucket e are
    valid; the rest are zero-masked (the reference's count semantics under
    static shapes).
    """
    t = ensure_tensor(x)
    ax = group if isinstance(group, str) else "ep"
    lc = ensure_tensor(local_count)._value if local_count is not None else None

    def f(a):
        if lc is not None:
            a = _mask_counts(a, lc)
        if not _in_spmd(ax):
            return a
        ep = _axis_size(ax)
        e_local = a.shape[0] // ep
        out = lax.all_to_all(a, ax, 0, 0, tiled=True)  # [ep*E_local, C, d]
        out = out.reshape(ep, e_local, a.shape[1], a.shape[2])
        return jnp.swapaxes(out, 0, 1).reshape(e_local, ep * a.shape[1],
                                               a.shape[2])

    return run_op(f, [t], "global_scatter")


def global_gather(x, local_count=None, global_count=None, group=None):
    """Inverse of global_scatter: [E_local, ep*C, d] -> [E, C, d]."""
    t = ensure_tensor(x)
    ax = group if isinstance(group, str) else "ep"
    gc = ensure_tensor(global_count)._value if global_count is not None else None

    def f(a):
        if not _in_spmd(ax):
            return a if gc is None else _mask_counts(a, gc)
        ep = _axis_size(ax)
        e_local, epc, d = a.shape
        c = epc // ep
        b = a.reshape(e_local, ep, c, d)
        b = jnp.swapaxes(b, 0, 1).reshape(ep * e_local, c, d)
        out = lax.all_to_all(b, ax, 0, 0, tiled=True)  # back to [E, C, d]
        if gc is not None:
            out = _mask_counts(out, gc)
        return out

    return run_op(f, [t], "global_gather")


# ---------------- the layer ----------------
class MoELayer:
    """Mixture-of-experts FFN block (incubate MoELayer role).

    Experts are stacked weights — the expert pass is one batched einsum.
    Call inside shard_map/SPMD with `ep_axis` set for expert parallelism;
    without a mesh it runs all experts locally (dense fallback used by the
    equivalence tests).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, ep_axis: Optional[str] = None,
                 seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        s1 = 1.0 / math.sqrt(d_model)
        s2 = 1.0 / math.sqrt(d_hidden)
        self.wg = jnp.asarray(rng.uniform(-s1, s1, (d_model, num_experts)),
                              dtype)
        self.w1 = jnp.asarray(rng.uniform(-s1, s1,
                                          (num_experts, d_model, d_hidden)), dtype)
        self.b1 = jnp.zeros((num_experts, d_hidden), dtype)
        self.w2 = jnp.asarray(rng.uniform(-s2, s2,
                                          (num_experts, d_hidden, d_model)), dtype)
        self.b2 = jnp.zeros((num_experts, d_model), dtype)
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        self.aux_loss = 0.0

    @staticmethod
    def _ffn(inp, w1, b1, w2, b2):
        """[E', C', d] through stacked expert FFNs — one batched einsum."""
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", inp, w1) + b1[:, None, :])
        return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]

    def _experts(self, inp):
        return self._ffn(inp, self.w1, self.b1, self.w2, self.b2)

    def __call__(self, x, capacity=None, return_aux=False):
        """x: [T, d] (flatten batch*seq first). Returns [T, d], or
        (out, aux_loss) with `return_aux=True`.

        Under jit/shard_map tracing, use `return_aux=True` — `self.aux_loss`
        is a trace-time side effect (stale on cached executions) kept only
        for eager convenience."""
        arr = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        logits = arr @ self.wg
        dispatch, combine, aux = top_k_gating(
            logits, self.top_k, capacity=capacity,
            capacity_factor=self.capacity_factor)
        self.aux_loss = aux
        buckets = moe_dispatch(arr, dispatch)                # [E, C, d]
        ax = self.ep_axis
        if ax is not None and _in_spmd(ax):
            ep = _axis_size(ax)
            e_local = self.num_experts // ep
            rank = lax.axis_index(ax)
            # tokens' buckets -> owning ranks; each rank runs ITS experts
            inp = global_scatter(Tensor(buckets), group=ax)._value
            out = self._local_expert_slice(inp, rank, e_local)
            out = global_gather(Tensor(out), group=ax)._value
        else:
            out = self._experts(buckets)
        y = moe_combine(out, combine)
        wrap = isinstance(x, Tensor)
        y = Tensor(y) if wrap else y
        if return_aux:
            return y, (Tensor(aux) if wrap else aux)
        return y

    def _local_expert_slice(self, inp, rank, e_local):
        # dynamic slice of stacked weights by mesh rank (traced index)
        sl = lambda w: lax.dynamic_slice_in_dim(w, rank * e_local, e_local, 0)  # noqa: E731
        return self._ffn(inp, sl(self.w1), sl(self.b1), sl(self.w2),
                         sl(self.b2))
