"""MoE expert-parallel alltoall utilities.

Reference parity: `operators/collective/global_scatter_op.cc` /
`global_gather_op.cc` + python wrappers (`distributed/utils.py:52-129`).
TPU-native: expert dispatch is `lax.all_to_all` over the 'mp' (or dedicated
'ep') axis inside an SPMD region, with capacity-bucketed dense tensors
(static shapes for XLA) instead of LoD-style variable counts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op
from .collective import _in_spmd


def global_scatter(x, local_count, global_count, group=None):
    t = ensure_tensor(x)
    ax = group if isinstance(group, str) else "mp"
    if _in_spmd(ax):
        return run_op(lambda a: lax.all_to_all(a, ax, 0, 0, tiled=True), [t],
                      "global_scatter")
    return t


def global_gather(x, local_count, global_count, group=None):
    t = ensure_tensor(x)
    ax = group if isinstance(group, str) else "mp"
    if _in_spmd(ax):
        return run_op(lambda a: lax.all_to_all(a, ax, 0, 0, tiled=True), [t],
                      "global_gather")
    return t


def moe_dispatch(x, gate_logits, num_experts, capacity_factor=1.25, axis_name="ep"):
    """Top-1 switch routing with static capacity (call inside shard_map).

    x: [tokens, d]; returns (expert_inputs [E_local, capacity, d], combine info).
    """
    tokens, d = x.shape
    capacity = int(capacity_factor * tokens / num_experts)
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    # position of each token within its expert bucket
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1
    pos_in_expert = jnp.sum(pos, axis=-1)
    keep = pos_in_expert < capacity

    buckets = jnp.zeros((num_experts, capacity, d), x.dtype)
    buckets = buckets.at[expert, jnp.clip(pos_in_expert, 0, capacity - 1)].add(
        jnp.where(keep[:, None], x, 0.0))
    return buckets, (expert, pos_in_expert, keep, gate, capacity)


def moe_combine(expert_out, dispatch_info):
    expert, pos_in_expert, keep, gate, capacity = dispatch_info
    gathered = expert_out[expert, jnp.clip(pos_in_expert, 0, capacity - 1)]
    return jnp.where(keep[:, None], gathered * gate[:, None], 0.0)
