"""Distributed launcher: `python -m paddle_tpu.distributed.launch train.py`.

Reference parity: `python/paddle/distributed/fleet/launch.py:523` (launch →
launch_collective:380 → start_local_trainers with PADDLE_* env).

TPU-native process model: ONE process per HOST (chips inside a host are
addressed by the mesh, not by processes), so on a single host the launcher
simply execs the script with rank env set; multi-host launch sets the
coordinator address for jax.distributed. `--nproc_per_node` is accepted for
CPU-mesh simulation (spawns N processes with a device-count override).
"""
from __future__ import annotations

import argparse
import os
import runpy
import subprocess
import sys


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=int, default=int(os.environ.get("PADDLE_NNODES", 1)))
    p.add_argument("--node_rank", type=int, default=int(os.environ.get("PADDLE_NODE_RANK", 0)))
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER", "127.0.0.1:6170"))
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--devices", "--gpus", dest="devices", default=None)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--run_mode", default=None,
                   help="collective (default) | ps | elastic")
    # PS mode (reference launch_ps, fleet/launch.py:416)
    p.add_argument("--server_num", type=int, default=0)
    p.add_argument("--worker_num", type=int, default=0)
    p.add_argument("--servers", default="", help="host:port list for PS")
    # elastic mode (reference launch_elastic, elastic/__init__.py:48)
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = p.parse_args()
    if args.run_mode is None:
        # mode autodetect (reference which_distributed_mode, launch.py:448)
        args.run_mode = "ps" if (args.server_num or args.servers
                                  or args.worker_num) else "collective"
    return args


def _launch_ps(args):
    """Server + trainer process gang (launch_ps role): servers get
    TRAINING_ROLE=PSERVER and a port; trainers get the endpoint list."""
    if args.servers:
        endpoints = [e for e in args.servers.split(",") if e]
    else:
        endpoints = [f"127.0.0.1:{8200 + i}" for i in range(args.server_num)]
    n_workers = args.worker_num or 1
    procs = []

    def spawn(role, rank, extra):
        env = dict(os.environ)
        env["TRAINING_ROLE"] = role
        env["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(endpoints)
        env["PADDLE_TRAINERS_NUM"] = str(n_workers)
        env.update(extra)
        out = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            out = open(os.path.join(args.log_dir,
                                    f"{role.lower()}log.{rank}"), "w")
        return subprocess.Popen(
            [sys.executable, args.training_script] + args.training_script_args,
            env=env, stdout=out, stderr=subprocess.STDOUT if out else None)

    for i, ep in enumerate(endpoints):
        procs.append(spawn("PSERVER", i, {
            "PADDLE_PORT": ep.rsplit(":", 1)[1], "POD_IP": ep.rsplit(":", 1)[0]}))
    for r in range(n_workers):
        procs.append(spawn("TRAINER", r, {"PADDLE_TRAINER_ID": str(r)}))
    rc = 0
    # trainers finish -> kill servers (reference behavior)
    for p in procs[len(endpoints):]:
        rc |= p.wait()
    for p in procs[:len(endpoints)]:
        p.terminate()
    sys.exit(rc)


def launch():
    args = _parse()
    if args.run_mode == "ps":
        return _launch_ps(args)
    if args.run_mode == "elastic":
        from .elastic import launch_elastic
        res = launch_elastic(args.training_script,
                             args.training_script_args,
                             nprocs=max(args.nproc_per_node, 1),
                             max_restarts=args.max_restarts)
        sys.exit(0 if res.success else 1)
    base_env = dict(os.environ)
    base_env["PADDLE_MASTER"] = args.master
    base_env["PADDLE_TRAINERS_NUM"] = str(args.nnodes * args.nproc_per_node)

    if args.nproc_per_node == 1:
        os.environ.update(base_env)
        os.environ["PADDLE_TRAINER_ID"] = str(args.node_rank)
        os.environ["PADDLE_CURRENT_ENDPOINT"] = args.master if args.node_rank == 0 \
            else f"127.0.0.1:{6171 + args.node_rank}"
        sys.argv = [args.training_script] + args.training_script_args
        runpy.run_path(args.training_script, run_name="__main__")
        return

    # multi-process simulation (CPU mesh per process)
    procs = []
    for local in range(args.nproc_per_node):
        rank = args.node_rank * args.nproc_per_node + local
        env = dict(base_env)
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_RANK_IN_NODE"] = str(local)
        env["PADDLE_CURRENT_ENDPOINT"] = f"127.0.0.1:{6171 + rank}"
        env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(
            f"127.0.0.1:{6171 + r}" for r in range(args.nnodes * args.nproc_per_node))
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            out = open(os.path.join(args.log_dir, f"workerlog.{rank}"), "w")
        else:
            out = None
        procs.append(subprocess.Popen(
            [sys.executable, args.training_script] + args.training_script_args,
            env=env, stdout=out, stderr=subprocess.STDOUT if out else None))
    rc = 0
    for p in procs:
        rc |= p.wait()
    sys.exit(rc)


if __name__ == "__main__":
    launch()
