"""Distributed launcher: `python -m paddle_tpu.distributed.launch train.py`.

Reference parity: `python/paddle/distributed/fleet/launch.py:523` (launch →
launch_collective:380 → start_local_trainers with PADDLE_* env).

TPU-native process model: ONE process per HOST (chips inside a host are
addressed by the mesh, not by processes), so on a single host the launcher
simply execs the script with rank env set; multi-host launch sets the
coordinator address for jax.distributed. `--nproc_per_node` is accepted for
CPU-mesh simulation (spawns N processes with a device-count override).
"""
from __future__ import annotations

import argparse
import os
import runpy
import subprocess
import sys


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=int, default=int(os.environ.get("PADDLE_NNODES", 1)))
    p.add_argument("--node_rank", type=int, default=int(os.environ.get("PADDLE_NODE_RANK", 0)))
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER", "127.0.0.1:6170"))
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--devices", "--gpus", dest="devices", default=None)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--run_mode", default="collective")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def launch():
    args = _parse()
    base_env = dict(os.environ)
    base_env["PADDLE_MASTER"] = args.master
    base_env["PADDLE_TRAINERS_NUM"] = str(args.nnodes * args.nproc_per_node)

    if args.nproc_per_node == 1:
        os.environ.update(base_env)
        os.environ["PADDLE_TRAINER_ID"] = str(args.node_rank)
        os.environ["PADDLE_CURRENT_ENDPOINT"] = args.master if args.node_rank == 0 \
            else f"127.0.0.1:{6171 + args.node_rank}"
        sys.argv = [args.training_script] + args.training_script_args
        runpy.run_path(args.training_script, run_name="__main__")
        return

    # multi-process simulation (CPU mesh per process)
    procs = []
    for local in range(args.nproc_per_node):
        rank = args.node_rank * args.nproc_per_node + local
        env = dict(base_env)
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_RANK_IN_NODE"] = str(local)
        env["PADDLE_CURRENT_ENDPOINT"] = f"127.0.0.1:{6171 + rank}"
        env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(
            f"127.0.0.1:{6171 + r}" for r in range(args.nnodes * args.nproc_per_node))
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            out = open(os.path.join(args.log_dir, f"workerlog.{rank}"), "w")
        else:
            out = None
        procs.append(subprocess.Popen(
            [sys.executable, args.training_script] + args.training_script_args,
            env=env, stdout=out, stderr=subprocess.STDOUT if out else None))
    rc = 0
    for p in procs:
        rc |= p.wait()
    sys.exit(rc)


if __name__ == "__main__":
    launch()
