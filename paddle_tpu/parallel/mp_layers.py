"""Tensor-parallel (megatron-style) layers — GSPMD sharding annotations.

Reference parity: `python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py:30,97,170,249` (VocabParallelEmbedding,
ColumnParallelLinear, RowParallelLinear, ParallelCrossEntropy).

TPU-native design: instead of manual `_c_identity/matmul/_mp_allreduce`
(collective.py:793-927 in the reference), each layer annotates its weight
with a PartitionSpec over the 'mp' mesh axis and constrains its activations;
XLA GSPMD inserts the all-reduce/all-gather on ICI. The same layers also
work inside `shard_map` regions (manual-collective regime) — the forward
detects a bound 'mp' axis and emits explicit lax collectives, which is what
the pipeline engine uses.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.jaxcompat import axis_size as _axis_size
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import Layer
from ..ops._dispatch import ensure_tensor, run_op
from .collective import _in_spmd
from .topology import get_mesh


def _constrain(arr, *spec):
    """Apply a sharding constraint when tracing under a mesh (GSPMD regime)."""
    mesh = get_mesh()
    if mesh is None or not isinstance(arr, jax.core.Tracer):
        return arr
    try:
        return lax.with_sharding_constraint(arr, NamedSharding(mesh, P(*spec)))
    except Exception:
        return arr


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on out ('mp'); output stays sharded unless
    gather_output."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=I.XavierNormal())
        self.weight.dist_attr = (None, "mp")
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None
        if self.bias is not None:
            self.bias.dist_attr = ("mp",)

    def forward(self, x):
        if _in_spmd("mp"):  # manual regime: local shard matmul
            out = F.linear(x, self.weight, self.bias)
            if self.gather_output:
                out = run_op(lambda a: lax.all_gather(a, "mp", axis=a.ndim - 1, tiled=True),
                             [out], "c_concat")
            return out
        out = F.linear(x, self.weight, self.bias)
        out._value = _constrain(out._value, *([None] * (out.ndim - 1) + ["mp"]))
        if self.gather_output:
            out._value = _constrain(out._value, *([None] * out.ndim))
        return out


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on in ('mp'); input expected sharded on last dim."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=I.XavierNormal())
        self.weight.dist_attr = ("mp", None)
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        x = ensure_tensor(x)
        if _in_spmd("mp"):  # manual regime: partial matmul + psum
            if not self.input_is_parallel:
                n = _axis_size("mp")
                idx = lax.axis_index("mp")

                def split_f(a):
                    sz = a.shape[-1] // n
                    return lax.dynamic_slice_in_dim(a, idx * sz, sz, axis=a.ndim - 1)

                x = run_op(split_f, [x], "c_split")
            partial = F.linear(x, self.weight)
            out = run_op(lambda a: lax.psum(a, "mp"), [partial], "mp_allreduce")
            if self.bias is not None:
                out = out + self.bias
            return out
        xin = x
        xin._value = _constrain(xin._value, *([None] * (x.ndim - 1) + ["mp"]))
        out = F.linear(xin, self.weight, self.bias)
        out._value = _constrain(out._value, *([None] * out.ndim))
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim over 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.weight = self.create_parameter([num_embeddings, embedding_dim],
                                            attr=weight_attr,
                                            default_initializer=I.Normal(0.0, 0.02))
        self.weight.dist_attr = ("mp", None)

    def forward(self, x):
        x = ensure_tensor(x)
        if _in_spmd("mp"):  # manual regime: mask out-of-shard ids, psum partial lookups
            n = _axis_size("mp")
            idx = lax.axis_index("mp")
            per = self.num_embeddings // n

            def f(w):
                ids = x._value.astype(jnp.int32)
                local = ids - idx * per
                in_shard = (local >= 0) & (local < per)
                safe = jnp.where(in_shard, local, 0)
                emb = jnp.take(w, safe, axis=0)
                emb = jnp.where(in_shard[..., None], emb, jnp.zeros((), emb.dtype))
                return lax.psum(emb, "mp")

            return run_op(f, [self.weight], "c_embedding")
        out = F.embedding(x, self.weight)
        out._value = _constrain(out._value, *([None] * out.ndim))
        return out


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE (`c_softmax_with_cross_entropy_op.cu:1`).

    GSPMD regime: plain CE over logits sharded on vocab — XLA partitions the
    log-softmax reduction. Manual regime: explicit max/sum psums over 'mp'.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input, label = ensure_tensor(input), ensure_tensor(label)
        if _in_spmd("mp"):
            n = _axis_size("mp")
            idx = lax.axis_index("mp")

            def f(logits):
                ids = label._value.astype(jnp.int32)
                if ids.ndim == logits.ndim:
                    ids = jnp.squeeze(ids, -1)
                vmax = lax.pmax(jnp.max(logits, -1, keepdims=True), "mp")
                ex = jnp.exp(logits - vmax)
                denom = lax.psum(jnp.sum(ex, -1, keepdims=True), "mp")
                per = logits.shape[-1]
                local = ids - idx * per
                in_shard = (local >= 0) & (local < per)
                safe = jnp.where(in_shard, local, 0)
                picked = jnp.take_along_axis(logits - vmax, safe[..., None], axis=-1)
                picked = jnp.where(in_shard[..., None], picked, jnp.zeros((), logits.dtype))
                picked = lax.psum(picked, "mp")
                return (jnp.log(denom) - picked)[..., 0][..., None]

            return run_op(f, [input], "c_softmax_with_cross_entropy")
        return F.softmax_with_cross_entropy(input, label)
