"""Elastic training: lease-based membership + gang relaunch.

Reference parity: `python/paddle/distributed/fleet/elastic/manager.py:130`
(ElasticManager: nodes register in etcd with TTL leases, watches trigger
membership changes, `manager.py:245-266`) and `elastic/__init__.py:48`
(launch_elastic: restart loop around the launcher). Env contract kept:
`PADDLE_ELASTIC_*`.

TPU-native redesign: etcd is replaced by the framework's own C++ TCPStore —
each node heartbeats a timestamp under `lease:{rank}`; staleness past the
TTL is the lease expiry; the single-host gang launcher kills and respawns
the whole gang on any member death (XLA SPMD jobs cannot run degraded, so
scale-in == restart with new membership, same as the reference's collective
mode).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import List, Optional, Sequence


class ElasticManager:
    """Lease-based membership over a TCPStore (manager.py:130 role)."""

    def __init__(self, store, rank: int, world_size: int,
                 lease_ttl: float = 10.0, heartbeat_interval: float = 2.0):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.lease_ttl = lease_ttl
        self.heartbeat_interval = heartbeat_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- node side --
    def register(self):
        self._beat()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _beat(self):
        self.store.set(f"lease:{self.rank}", repr(time.time()))

    def _run(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except Exception:
                return  # store gone: the watcher will see our lease expire

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # -- watcher side --
    def alive_ranks(self) -> List[int]:
        now = time.time()
        alive = []
        for r in range(self.world_size):
            try:
                ts = float(self.store.get(f"lease:{r}").decode())
            except KeyError:
                continue
            if now - ts <= self.lease_ttl:
                alive.append(r)
        return alive

    def dead_ranks(self) -> List[int]:
        alive = set(self.alive_ranks())
        return [r for r in range(self.world_size) if r not in alive]

    def watch(self, interval: float = 1.0, max_wait: Optional[float] = None):
        """Block until membership shrinks; returns the dead ranks."""
        start = time.time()
        while True:
            dead = self.dead_ranks()
            if dead:
                return dead
            if max_wait is not None and time.time() - start > max_wait:
                return []
            time.sleep(interval)


class ElasticResult:
    def __init__(self, restarts: int, returncodes: Sequence[int]):
        self.restarts = restarts
        self.returncodes = list(returncodes)

    @property
    def success(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)


def launch_elastic(training_script: str, script_args: Sequence[str] = (),
                   nprocs: int = 2, max_restarts: int = 3,
                   poll_interval: float = 0.2, env: Optional[dict] = None,
                   timeout: float = 300.0) -> ElasticResult:
    """Gang launcher with relaunch loop (elastic/__init__.py:48 role).

    Spawns `nprocs` ranks of `training_script`; if ANY rank dies non-zero,
    the remaining ranks are killed and the whole gang is relaunched (up to
    `max_restarts` times) with PADDLE_ELASTIC_RESTART_COUNT advanced —
    collective jobs restart as a unit, matching the reference's collective
    elastic mode.
    """
    base_env = dict(os.environ if env is None else env)
    for attempt in range(max_restarts + 1):
        procs = []
        for r in range(nprocs):
            e = dict(base_env)
            e.update({
                "PADDLE_TRAINER_ID": str(r),
                "PADDLE_TRAINERS_NUM": str(nprocs),
                "PADDLE_ELASTIC_RESTART_COUNT": str(attempt),
                "PADDLE_ELASTIC_NP": str(nprocs),
            })
            procs.append(subprocess.Popen(
                [sys.executable, training_script, *map(str, script_args)],
                env=e))
        deadline = time.time() + timeout
        failed = False
        while True:
            rcs = [p.poll() for p in procs]
            if any(rc is not None and rc != 0 for rc in rcs):
                failed = True
                break
            if all(rc == 0 for rc in rcs):
                break
            if time.time() > deadline:
                failed = True
                break
            time.sleep(poll_interval)
        if not failed:
            return ElasticResult(attempt, [p.returncode for p in procs])
        for p in procs:  # kill the rest of the gang, then relaunch
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    return ElasticResult(max_restarts, [p.returncode for p in procs])
