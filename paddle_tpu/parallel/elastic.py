"""Elastic training: lease-based membership + gang relaunch.

Reference parity: `python/paddle/distributed/fleet/elastic/manager.py:130`
(ElasticManager: nodes register in etcd with TTL leases, watches trigger
membership changes, `manager.py:245-266`) and `elastic/__init__.py:48`
(launch_elastic: restart loop around the launcher). Env contract kept:
`PADDLE_ELASTIC_*`.

TPU-native redesign: etcd is replaced by the framework's own C++ TCPStore —
each node heartbeats a timestamp under `lease:{rank}`; staleness past the
TTL is the lease expiry; the single-host gang launcher kills and respawns
the whole gang on any member death (XLA SPMD jobs cannot run degraded, so
scale-in == restart with new membership, same as the reference's collective
mode).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import List, Optional, Sequence

from .. import faults as _faults
from .. import monitor as _monitor
from ..utils import syncwatch as _syncwatch


class PrefixStore:
    """Namespace adapter so one TCPStore hosts many planes: every key a
    consumer writes (ElasticManager leases, join tickets, the PS HA
    primary record) lands under its own prefix. Grew up in the serving
    fleet; promoted here because the PS HA plane shares it."""

    def __init__(self, store, prefix: str):
        self._store = store
        self._prefix = prefix

    def set(self, key, value):
        return self._store.set(self._prefix + key, value)

    def get(self, key):
        return self._store.get(self._prefix + key)

    def add(self, key, amount):
        return self._store.add(self._prefix + key, amount)

    def wait(self, keys, timeout=None):
        return self._store.wait([self._prefix + k for k in keys], timeout)


class ElasticManager:
    """Lease-based membership over a TCPStore (manager.py:130 role)."""

    def __init__(self, store, rank: int, world_size: int,
                 lease_ttl: float = 10.0, heartbeat_interval: float = 2.0):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.lease_ttl = lease_ttl
        self.heartbeat_interval = heartbeat_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # on_rank_dead plane: callbacks fired once per lease-expiry
        # TRANSITION (a rank that heartbeats again re-arms), driven by a
        # dedicated watcher thread so callers don't have to poll
        # alive_ranks themselves
        self._dead_cbs: List = []
        self._known_dead: set = set()
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None

    # -- node side --
    def register(self):
        self._beat()
        self._thread = _syncwatch.Thread(target=self._run, daemon=True,
                                        name="elastic-heartbeat")
        self._thread.start()
        return self

    def _beat(self):
        if _faults._ENABLED:
            _faults.check("elastic.heartbeat")
        self.store.set(f"lease:{self.rank}", repr(time.time()))

    def _run(self):
        # a TRANSIENT store error (blip, injected fault) must not kill the
        # heartbeat thread — that would turn a one-interval hiccup into a
        # permanent lease expiry. Retry next interval; only give up once
        # the failures alone would have expired the lease anyway.
        misses = 0
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
                misses = 0
            except Exception:
                misses += 1
                if _monitor._ENABLED:
                    _monitor.count("elastic.heartbeat_errors")
                if misses * self.heartbeat_interval > self.lease_ttl * 3:
                    return  # store genuinely gone: lease is long expired

    def reclaim(self, rank: int) -> None:
        """Forcibly expire `rank`'s lease (the store has no delete: an
        empty value reads as expired). The autoscaler uses this to
        reclaim a corpse's lease after a SIGKILL mid-drain or a spawn
        that never came up — membership converges immediately instead of
        waiting out the TTL."""
        self.store.set(f"lease:{rank}", b"")

    def stop(self):
        self._stop.set()
        self._watch_stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2)
            self._watch_thread = None

    # -- watcher side --
    def on_rank_dead(self, callback, interval: Optional[float] = None):
        """Register `callback(rank)` to fire ONCE per lease-expiry
        transition (the fleet router and tests react to replica death
        promptly instead of polling `alive_ranks`). Each newly expired
        lease also counts `elastic.lease_expired`. A rank whose lease
        recovers (rejoin) re-arms: a later expiry fires again. The first
        registration starts the `elastic-watcher` thread; `stop()` ends
        it."""
        self._dead_cbs.append(callback)
        if self._watch_thread is None:
            iv = interval if interval is not None else \
                min(1.0, self.heartbeat_interval)
            self._watch_stop.clear()
            self._watch_thread = _syncwatch.Thread(
                target=self._watch_loop, args=(iv,), daemon=True,
                name="elastic-watcher")
            self._watch_thread.start()
        return self

    def _watch_loop(self, interval: float) -> None:
        ever_alive: set = set()
        while not self._watch_stop.wait(interval):
            try:
                alive = set(self.alive_ranks())
            except Exception:
                continue  # transient store blip: check next interval
            ever_alive |= alive
            # only a rank that was OBSERVED alive can expire — a fleet
            # watching a sparse id space must not page for ids that never
            # registered
            dead = ever_alive - alive
            fresh = dead - self._known_dead
            self._known_dead = dead  # recovered ranks re-arm implicitly
            for r in sorted(fresh):
                if _monitor._ENABLED:
                    _monitor.count("elastic.lease_expired")
                for cb in list(self._dead_cbs):
                    try:
                        cb(r)
                    except Exception:
                        pass  # one bad callback must not kill the watcher
    def alive_ranks(self) -> List[int]:
        now = time.time()
        alive = []
        for r in range(self.world_size):
            try:
                ts = float(self.store.get(f"lease:{r}").decode())
            except (KeyError, ValueError):
                # missing OR undecodable (truncated/garbled write) lease ==
                # expired; a corrupt value must not crash the watcher
                # thread (same contract pending_joins already applies)
                continue
            if now - ts <= self.lease_ttl:
                alive.append(r)
        return alive

    def dead_ranks(self) -> List[int]:
        alive = set(self.alive_ranks())
        return [r for r in range(self.world_size) if r not in alive]

    def watch(self, interval: float = 1.0, max_wait: Optional[float] = None):
        """Block until membership shrinks; returns the dead ranks."""
        start = time.time()
        while True:
            dead = self.dead_ranks()
            if dead:
                return dead
            if max_wait is not None and time.time() - start > max_wait:
                return []
            time.sleep(interval)

    # -- scale-out (manager.py:215-266 world-size-change role) --
    def announce_join(self, node_id: str = "") -> int:
        """A NEW node announces itself to the gang's store; returns its
        join ticket. The controller absorbs pending tickets at the next
        re-rendezvous, growing the world size."""
        seq = self.store.add("elastic:join_seq", 1)
        self.store.set(f"elastic:join:{seq}",
                       f"{time.time()!r}:{node_id}")
        return seq

    def pending_joins(self, absorbed: int = 0) -> List[int]:
        """Join tickets newer than `absorbed` whose announcement is still
        fresh (within the lease TTL x 6 — joiners wait for the gang)."""
        try:
            # add(0) reads the counter (native add-counters live in their
            # own namespace; plain get can't see them)
            seq = int(self.store.add("elastic:join_seq", 0))
        except Exception:
            return []
        now = time.time()
        out = []
        for i in range(absorbed + 1, seq + 1):
            try:
                raw = self.store.get(f"elastic:join:{i}").decode()
                ts = float(raw.split(":", 1)[0])
            except (KeyError, ValueError):
                continue
            if now - ts <= self.lease_ttl * 6:
                out.append(i)
        return out

    def watch_membership(self, interval: float = 1.0,
                         max_wait: Optional[float] = None,
                         absorbed: int = 0):
        """Block until membership CHANGES either way:
        ('scale_in', dead_ranks) | ('scale_out', join_tickets) |
        ('steady', []) on timeout."""
        start = time.time()
        while True:
            dead = self.dead_ranks()
            if dead:
                return ("scale_in", dead)
            joins = self.pending_joins(absorbed)
            if joins:
                return ("scale_out", joins)
            if max_wait is not None and time.time() - start > max_wait:
                return ("steady", [])
            time.sleep(interval)


class ElasticResult:
    def __init__(self, restarts: int, returncodes: Sequence[int]):
        self.restarts = restarts
        self.returncodes = list(returncodes)

    @property
    def success(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)


def launch_elastic(training_script: str, script_args: Sequence[str] = (),
                   nprocs: int = 2, max_restarts: int = 3,
                   poll_interval: float = 0.2, env: Optional[dict] = None,
                   timeout: float = 300.0, store=None,
                   max_np: Optional[int] = None) -> ElasticResult:
    """Gang launcher with relaunch + scale loop (elastic/__init__.py:48 +
    manager.py:215-266 world-size-change roles).

    Spawns `nprocs` ranks of `training_script`. Events:
    - a rank dying non-zero kills the gang and relaunches it (up to
      `max_restarts` times) — collective jobs restart as a unit;
    - with a `store`, a join announcement (ElasticManager.announce_join
      from a NEW node) triggers a re-rendezvous: the gang is killed and
      relaunched with world size grown by the pending joins (capped at
      `max_np`). Scale events do NOT consume the failure budget.
    Each (re)launch exports the CURRENT world size via
    PADDLE_TRAINERS_NUM/PADDLE_ELASTIC_NP, so AutoCheckpoint-driven
    scripts restore their snapshot and resume at the new membership.
    """
    base_env = dict(os.environ if env is None else env)
    watcher = ElasticManager(store, rank=-1, world_size=0) if store else None
    absorbed = 0
    attempt = 0      # failure count (scale events don't advance it)
    launches = 0
    np_now = nprocs
    procs: List[subprocess.Popen] = []
    while attempt <= max_restarts:
        procs = []
        for r in range(np_now):
            e = dict(base_env)
            e.update({
                "PADDLE_TRAINER_ID": str(r),
                "PADDLE_TRAINERS_NUM": str(np_now),
                "PADDLE_ELASTIC_RESTART_COUNT": str(launches),
                "PADDLE_ELASTIC_NP": str(np_now),
            })
            procs.append(subprocess.Popen(
                [sys.executable, training_script, *map(str, script_args)],
                env=e))
        launches += 1
        deadline = time.time() + timeout
        outcome = "done"
        while True:
            rcs = [p.poll() for p in procs]
            if any(rc is not None and rc != 0 for rc in rcs):
                outcome = "failed"
                break
            if all(rc == 0 for rc in rcs):
                break
            if watcher is not None:
                joins = watcher.pending_joins(absorbed)
                # Partial absorption: grow whenever there is headroom at
                # all — the absorb slice below caps how many join.
                if joins and (max_np is None or np_now < max_np):
                    outcome = "scale_out"
                    break
            if time.time() > deadline:
                outcome = "failed"
                break
            time.sleep(poll_interval)
        if outcome == "done":
            return ElasticResult(attempt, [p.returncode for p in procs])
        for p in procs:  # kill the rest of the gang, then relaunch
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if outcome == "scale_out":
            joins = watcher.pending_joins(absorbed)
            take = joins if max_np is None else \
                joins[:max(0, max_np - np_now)]
            absorbed = max(take or [absorbed])
            np_now += len(take)
        else:
            attempt += 1
    return ElasticResult(max_restarts, [p.returncode for p in procs])
