"""Process-level distributed environment.

Reference parity: `python/paddle/distributed/parallel.py:79`
(init_parallel_env) + ParallelEnv, env vars PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS set by the launcher.

TPU-first: one process per HOST (not per chip); in-process chips are
addressed by the mesh, cross-host via jax.distributed (coordination service
= the reference's TCPStore role; see paddle_tpu._native.tcpstore for the
C++ rendezvous used to exchange the coordinator address when no scheduler
provides one).
"""
from __future__ import annotations

import os

import jax

_INITIALIZED = [False]


def get_rank(group=None) -> int:
    env = os.environ.get("PADDLE_TRAINER_ID")
    if env is not None:
        return int(env)
    try:
        return jax.process_index()
    except Exception:
        return 0


def get_world_size(group=None) -> int:
    env = os.environ.get("PADDLE_TRAINERS_NUM")
    if env is not None:
        return int(env)
    try:
        return jax.process_count()
    except Exception:
        return 1


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_RANK_IN_NODE", 0))

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]


def init_parallel_env(strategy=None):
    """Bring up cross-host coordination when endpoints are provided.

    Single-host (the common TPU-pod-slice-per-host case during tests) is a
    no-op: all chips are already visible to this process.
    """
    if _INITIALIZED[0]:
        return ParallelEnv()
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
    # world size/rank from env ONLY here: jax.process_count() would
    # initialize the XLA backend, after which initialize() is illegal
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if eps and nproc > 1:
        already = False
        try:
            from jax._src import distributed as _jd
            already = _jd.global_state.client is not None
        except Exception:
            pass
        if not already:
            coordinator = eps.split(",")[0]
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=nproc, process_id=rank)
    _INITIALIZED[0] = True
    return ParallelEnv()
