"""TensorParallel / ShardingParallel model wrappers.

Reference parity: `fleet/meta_parallel/tensor_parallel.py` and
`meta_parallel/sharding/*`. On TPU these wrappers carry the mesh + stage
config; the actual partitioning happens in SPMDTrainStep via the sharding
specs that mp_layers put on their weights.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self.hcg = hcg
        self.strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._layers.set_state_dict(sd, *a, **kw)


ShardingParallel = TensorParallel
