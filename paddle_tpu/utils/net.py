"""Shared socket framing helpers (used by the PS RPC plane and the
inference C-API server — one implementation of exact-read, plus the
inference response status frame).

Inference response statuses (csrc/predict_capi.cpp mirrors these): a
client must be able to tell backpressure (retryable, the server is
healthy) from failure — overload and deadline expiry get their own codes
instead of riding the generic error status.
"""
from __future__ import annotations

import struct
import time

# response status byte of the inference wire protocol
STATUS_OK = 0            # payload: u32 n_tensors + tensors
STATUS_ERROR = 1         # payload: u32 len + utf-8 message
STATUS_OVERLOADED = 2    # payload: u32 len + message; retry with backoff
STATUS_DEADLINE = 3      # payload: u32 len + message; request expired

_RESP_MAGIC = 0x50445253  # 'PDRS'

# 'PDTC' — OPTIONAL trace-context prefix frame: u32 magic + the 26-byte
# context of obs/trace.py (u8 version, 16B trace id, 8B span id, u8
# flags), sent by a tracing client immediately BEFORE its 'PDRQ'/'PDRD'
# request frame. Absence means "no trace": an untraced exchange is
# byte-identical to the pre-PDTC protocol, so old clients and servers
# interoperate with new ones.
TRACE_MAGIC = 0x50445443  # 'PDTC'

# Fleet-tier control frames (serving/fleet.py). Same back-compat
# discipline as 'PDTC': every frame is opt-in, absence means the
# single-replica protocol, so a fleet router can talk to a pre-fleet
# server (it just cannot drain it).
#
# 'PDDR' — graceful drain (no body). The replica stops accepting new
#   work (its listening port CLOSES, new requests on live connections
#   get STATUS_OVERLOADED), finishes every in-flight/queued batch,
#   deregisters its lease, then answers STATUS_OK + u32 len + JSON drain
#   report on the control connection.
DRAIN_MAGIC = 0x50444452  # 'PDDR'
# 'PDMQ' — OPTIONAL model-select prefix: u32 len + utf-8 model name,
#   sent before 'PDRQ'/'PDRD' to route the request to a named hosted
#   model (multi-model replicas). Absence = the default model.
MODEL_MAGIC = 0x50444D51  # 'PDMQ'
# 'PDMV' — model version control: u32 len + JSON {op: reload|rollback,
#   model: name}; answers STATUS_OK + u32 len + JSON {ok, version, ...}.
#   `reload` re-reads the newest committed generation of the tenant's
#   versioned weight store; `rollback` promotes the guard checkpoint
#   .bak generation first (instant rollback of a bad push).
MODEL_CTL_MAGIC = 0x50444D56  # 'PDMV'
# LLM streaming-generation frames (serving/llm.py). Opt-in like every
# extension above: a client that never sends 'PDSQ' sees the exact
# pre-streaming protocol, and the stream itself ends in a standard
# 'PDRS' status frame so error/overload/deadline handling is shared
# with the batch path.
#
# 'PDSQ' — streaming generation request: u32 magic + u32 max_new_tokens
#   + u32 deadline_ms (0 = none) + u32 n_tensors (=1) + one 1-D i32
#   prompt tensor in the standard tensor framing.
STREAM_REQ_MAGIC = 0x50445351  # 'PDSQ'
# 'PDST' — one streamed token, sent the moment the scheduler emits it:
#   u32 magic + u32 token index + i32 token id. The terminal 'PDRS'
#   carries STATUS_OK + u32 n=1 + the full i32 token tensor (so a
#   non-incremental caller can ignore 'PDST' frames it already read),
#   or STATUS_ERROR/OVERLOADED/DEADLINE + message.
STREAM_MAGIC = 0x50445354  # 'PDST'
# Fleet-telemetry frames (obs/telemetry.py). Unlike the serving frames
# above these carry a CRC: telemetry crosses process boundaries under
# churn (exporters reconnect mid-write after a collector SIGKILL), and a
# half-written frame must be detected and dropped, never half-parsed.
#
# 'PDTM' — telemetry push (exporter -> collector): CRC frame whose JSON
#   body is {"op": hello|metrics|events|query, ...}.
PDTM_MAGIC = 0x5044544D  # 'PDTM'
# 'PDTA' — telemetry ack (collector -> exporter): CRC frame whose JSON
#   body is {"ok": bool, "commands": [...]} — the ack doubles as the
#   collector's command channel (correlated incident dump fan-out).
PDTA_MAGIC = 0x50445441  # 'PDTA'


def send_crc_frame(sock, magic: int, payload: bytes) -> None:
    """Send `magic + crc32(payload) + len + payload` (all u32 LE)."""
    import zlib
    sock.sendall(struct.pack("<III", magic, zlib.crc32(payload),
                             len(payload)) + payload)


def recv_crc_frame(sock, expect_magic: int,
                   deadline: float | None = None) -> bytes:
    """Read one CRC frame; verify magic and checksum. Raises ValueError
    on either mismatch (caller drops the connection — a telemetry stream
    is resynchronized by reconnecting, not by scanning for a magic)."""
    import zlib
    magic, crc, n = struct.unpack("<III", recv_exact(sock, 12, deadline))
    if magic != expect_magic:
        raise ValueError(f"crc frame: magic 0x{magic:08X} != "
                         f"expected 0x{expect_magic:08X}")
    if n > (64 << 20):
        raise ValueError(f"crc frame: implausible length {n}")
    payload = recv_exact(sock, n, deadline)
    if zlib.crc32(payload) != crc:
        raise ValueError("crc frame: checksum mismatch")
    return payload


def send_trace_frame(sock, ctx) -> None:
    """Send the 'PDTC' prefix for a traced request (`ctx` is an
    obs.trace.TraceContext)."""
    from ..obs import trace as _trace
    sock.sendall(struct.pack("<I", TRACE_MAGIC) + _trace.pack_ctx(ctx))


def recv_trace_frame(sock, deadline: float | None = None):
    """Read the 'PDTC' body (the magic itself was already consumed by the
    caller's dispatch read). Returns a TraceContext, or None on a corrupt
    body (a trace must never break serving)."""
    from ..obs import trace as _trace
    raw = recv_exact(sock, _trace.CTX_WIRE_LEN, deadline)
    try:
        return _trace.unpack_ctx(raw)
    except (ValueError, struct.error):
        return None


def send_status_frame(sock, status: int, msg: bytes | str = b"") -> None:
    """Send a non-OK inference response frame: magic + status + message.
    One implementation so the server cannot desynchronize the stream by
    hand-rolling a frame per call site."""
    if isinstance(msg, str):
        msg = msg.encode()
    sock.sendall(struct.pack("<IB", _RESP_MAGIC, status)
                 + struct.pack("<I", len(msg)) + msg)


def recv_exact(sock, n: int, deadline: float | None = None) -> bytes:
    """Read exactly n bytes. `deadline` (absolute `time.monotonic()`
    seconds) bounds the TOTAL wait: a peer that stalls without closing —
    invisible to a plain blocking recv — raises TimeoutError instead of
    hanging the reader forever. The socket's own timeout is restored on
    exit, so callers with persistent connections are unaffected."""
    if n < 0:
        raise ValueError(f"recv_exact: negative length {n}")
    buf = bytearray()
    old_timeout = sock.gettimeout() if deadline is not None else None
    try:
        while len(buf) < n:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"recv_exact: deadline exceeded with "
                        f"{n - len(buf)} of {n} bytes outstanding")
                sock.settimeout(remaining)
            try:
                chunk = sock.recv(n - len(buf))
            except TimeoutError:  # socket.timeout aliases this on 3.10+
                raise TimeoutError(
                    f"recv_exact: peer stalled with {n - len(buf)} of {n} "
                    "bytes outstanding") from None
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
    finally:
        if deadline is not None:
            try:
                sock.settimeout(old_timeout)
            except OSError:
                pass
    return bytes(buf)
