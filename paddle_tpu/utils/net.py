"""One wire: the unified RPC substrate every plane dispatches through.

Framing helpers (exact-read, CRC frames, the inference status frame)
plus the connection-owning substrate — `RpcChannel` on the client side,
`RpcServer` on the server side — shared by all four wire planes:

  - serving   'PD??' verbs      (inference/server.py, serving/fleet.py)
  - PS        CMD_* wire        (distributed/ps/service.py)
  - bus       length + pickle   (distributed/fleet_executor.py)
  - telemetry 'PDTM'/'PDTA'     (obs/telemetry.py)

The substrate owns the transport concerns each plane used to hand-roll:
connect/reconnect, resolver re-resolution (PS HA failover, fleet
routing), bounded retry with exponential backoff + full jitter,
absolute-deadline bookkeeping (and optional on-wire propagation so a
server drops expired work instead of computing it), named `faults.py`
sites (`net.<plane>.send` / `net.<plane>.recv`), monitor counters
(`net.retries` / `net.reconnects` / `net.crc_errors` /
`net.deadline_drops` / `net.auth_rejects`), and — the payoff of a
single substrate — optional per-frame HMAC auth (`FLAGS_net_auth_token`)
and TLS (`FLAGS_net_tls_cert/key/ca`) that secure every plane with one
flag flip. Each plane keeps its own verb framing as a codec over the
channel, so with auth/TLS off the wire bytes are BIT-IDENTICAL to the
pre-substrate protocols (golden-bytes tested in tests/test_net.py).

Inference response statuses (csrc/predict_capi.cpp mirrors these): a
client must be able to tell backpressure (retryable, the server is
healthy) from failure — overload and deadline expiry get their own codes
instead of riding the generic error status.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac_mod
import os
import random
import socket as _socket_mod
import struct
import threading
import time
import weakref

# response status byte of the inference wire protocol
STATUS_OK = 0            # payload: u32 n_tensors + tensors
STATUS_ERROR = 1         # payload: u32 len + utf-8 message
STATUS_OVERLOADED = 2    # payload: u32 len + message; retry with backoff
STATUS_DEADLINE = 3      # payload: u32 len + message; request expired

_RESP_MAGIC = 0x50445253  # 'PDRS'

# 'PDTC' — OPTIONAL trace-context prefix frame: u32 magic + the 26-byte
# context of obs/trace.py (u8 version, 16B trace id, 8B span id, u8
# flags), sent by a tracing client immediately BEFORE its 'PDRQ'/'PDRD'
# request frame. Absence means "no trace": an untraced exchange is
# byte-identical to the pre-PDTC protocol, so old clients and servers
# interoperate with new ones.
TRACE_MAGIC = 0x50445443  # 'PDTC'

# Fleet-tier control frames (serving/fleet.py). Same back-compat
# discipline as 'PDTC': every frame is opt-in, absence means the
# single-replica protocol, so a fleet router can talk to a pre-fleet
# server (it just cannot drain it).
#
# 'PDDR' — graceful drain (no body). The replica stops accepting new
#   work (its listening port CLOSES, new requests on live connections
#   get STATUS_OVERLOADED), finishes every in-flight/queued batch,
#   deregisters its lease, then answers STATUS_OK + u32 len + JSON drain
#   report on the control connection.
DRAIN_MAGIC = 0x50444452  # 'PDDR'
# 'PDMQ' — OPTIONAL model-select prefix: u32 len + utf-8 model name,
#   sent before 'PDRQ'/'PDRD' to route the request to a named hosted
#   model (multi-model replicas). Absence = the default model.
MODEL_MAGIC = 0x50444D51  # 'PDMQ'
# 'PDMV' — model version control: u32 len + JSON {op: reload|rollback,
#   model: name}; answers STATUS_OK + u32 len + JSON {ok, version, ...}.
#   `reload` re-reads the newest committed generation of the tenant's
#   versioned weight store; `rollback` promotes the guard checkpoint
#   .bak generation first (instant rollback of a bad push).
MODEL_CTL_MAGIC = 0x50444D56  # 'PDMV'
# LLM streaming-generation frames (serving/llm.py). Opt-in like every
# extension above: a client that never sends 'PDSQ' sees the exact
# pre-streaming protocol, and the stream itself ends in a standard
# 'PDRS' status frame so error/overload/deadline handling is shared
# with the batch path.
#
# 'PDSQ' — streaming generation request: u32 magic + u32 max_new_tokens
#   + u32 deadline_ms (0 = none) + u32 n_tensors (=1) + one 1-D i32
#   prompt tensor in the standard tensor framing.
STREAM_REQ_MAGIC = 0x50445351  # 'PDSQ'
# 'PDST' — one streamed token, sent the moment the scheduler emits it:
#   u32 magic + u32 token index + i32 token id. The terminal 'PDRS'
#   carries STATUS_OK + u32 n=1 + the full i32 token tensor (so a
#   non-incremental caller can ignore 'PDST' frames it already read),
#   or STATUS_ERROR/OVERLOADED/DEADLINE + message.
STREAM_MAGIC = 0x50445354  # 'PDST'
# Fleet-telemetry frames (obs/telemetry.py). Unlike the serving frames
# above these carry a CRC: telemetry crosses process boundaries under
# churn (exporters reconnect mid-write after a collector SIGKILL), and a
# half-written frame must be detected and dropped, never half-parsed.
#
# 'PDTM' — telemetry push (exporter -> collector): CRC frame whose JSON
#   body is {"op": hello|metrics|events|query, ...}.
PDTM_MAGIC = 0x5044544D  # 'PDTM'
# 'PDTA' — telemetry ack (collector -> exporter): CRC frame whose JSON
#   body is {"ok": bool, "commands": [...]} — the ack doubles as the
#   collector's command channel (correlated incident dump fan-out).
PDTA_MAGIC = 0x50445441  # 'PDTA'


def send_crc_frame(sock, magic: int, payload: bytes) -> None:
    """Send `magic + crc32(payload) + len + payload` (all u32 LE)."""
    import zlib
    sock.sendall(struct.pack("<III", magic, zlib.crc32(payload),
                             len(payload)) + payload)


def recv_crc_frame(sock, expect_magic: int,
                   deadline: float | None = None) -> bytes:
    """Read one CRC frame; verify magic and checksum. Raises ValueError
    on either mismatch (caller drops the connection — a telemetry stream
    is resynchronized by reconnecting, not by scanning for a magic)."""
    import zlib
    magic, crc, n = struct.unpack("<III", recv_exact(sock, 12, deadline))
    if magic != expect_magic:
        _count("net.crc_errors")
        raise ValueError(f"crc frame: magic 0x{magic:08X} != "
                         f"expected 0x{expect_magic:08X}")
    if n > (64 << 20):
        _count("net.crc_errors")
        raise ValueError(f"crc frame: implausible length {n}")
    payload = recv_exact(sock, n, deadline)
    if zlib.crc32(payload) != crc:
        _count("net.crc_errors")
        raise ValueError("crc frame: checksum mismatch")
    return payload


def send_trace_frame(sock, ctx) -> None:
    """Send the 'PDTC' prefix for a traced request (`ctx` is an
    obs.trace.TraceContext)."""
    from ..obs import trace as _trace
    sock.sendall(struct.pack("<I", TRACE_MAGIC) + _trace.pack_ctx(ctx))


def recv_trace_frame(sock, deadline: float | None = None):
    """Read the 'PDTC' body (the magic itself was already consumed by the
    caller's dispatch read). Returns a TraceContext, or None on a corrupt
    body (a trace must never break serving)."""
    from ..obs import trace as _trace
    raw = recv_exact(sock, _trace.CTX_WIRE_LEN, deadline)
    try:
        return _trace.unpack_ctx(raw)
    except (ValueError, struct.error):
        return None


def send_status_frame(sock, status: int, msg: bytes | str = b"") -> None:
    """Send a non-OK inference response frame: magic + status + message.
    One implementation so the server cannot desynchronize the stream by
    hand-rolling a frame per call site."""
    if isinstance(msg, str):
        msg = msg.encode()
    sock.sendall(struct.pack("<IB", _RESP_MAGIC, status)
                 + struct.pack("<I", len(msg)) + msg)


def recv_exact(sock, n: int, deadline: float | None = None) -> bytes:
    """Read exactly n bytes. `deadline` (absolute `time.monotonic()`
    seconds) bounds the TOTAL wait: a peer that stalls without closing —
    invisible to a plain blocking recv — raises TimeoutError instead of
    hanging the reader forever. The socket's own timeout is restored on
    exit, so callers with persistent connections are unaffected."""
    if n < 0:
        raise ValueError(f"recv_exact: negative length {n}")
    buf = bytearray()
    old_timeout = sock.gettimeout() if deadline is not None else None
    try:
        while len(buf) < n:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"recv_exact: deadline exceeded with "
                        f"{n - len(buf)} of {n} bytes outstanding")
                sock.settimeout(remaining)
            try:
                chunk = sock.recv(n - len(buf))
            except TimeoutError:  # socket.timeout aliases this on 3.10+
                raise TimeoutError(
                    f"recv_exact: peer stalled with {n - len(buf)} of {n} "
                    "bytes outstanding") from None
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
    finally:
        if deadline is not None:
            try:
                sock.settimeout(old_timeout)
            except OSError:
                pass
    return bytes(buf)


# sendmsg takes at most IOV_MAX iovecs per call; batch conservatively so
# a huge replication frame list never trips EINVAL on a small-limit OS.
_IOV_BATCH = 1024


def send_frames(sock, frames) -> None:
    """Send a list of bytes-like frames as one contiguous wire stream.

    On a plain TCP socket this is vectored I/O (`sendmsg`, i.e.
    writev): the kernel gathers the frames, so a caller holding N
    already-encoded records never pays the O(total) `b"".join` copy.
    Any wrapped socket (auth record layer, TLS) only exposes
    `sendall` semantics — there the frames are joined and sent
    through the wrapper, which keeps its framing/HMAC intact. The
    receiver cannot tell the difference: the bytes on the wire are
    identical either way.
    """
    frames = [f if isinstance(f, (bytes, bytearray, memoryview))
              else bytes(f) for f in frames]
    frames = [f for f in frames if len(f)]
    if not frames:
        return
    if type(sock) is not _socket_mod.socket or \
            not hasattr(sock, "sendmsg"):
        sock.sendall(b"".join(frames))
        return
    views = [memoryview(f).cast("B") for f in frames]
    idx = 0
    while idx < len(views):
        batch = views[idx:idx + _IOV_BATCH]
        sent = sock.sendmsg(batch)
        # Advance past whole frames the kernel took, then trim the
        # partial one; short sends are normal under backpressure.
        while batch and sent >= len(batch[0]):
            sent -= len(batch[0])
            batch.pop(0)
            idx += 1
        if batch and sent:
            views[idx] = batch[0][sent:]


# =============================================================================
# The unified RPC substrate. Everything below is shared by the four wire
# planes; everything above is the framing vocabulary they speak over it.
# =============================================================================

from .. import faults as _faults      # noqa: E402
from .. import monitor as _monitor    # noqa: E402
from ..core import flags as _flags    # noqa: E402
from . import syncwatch as _syncwatch  # noqa: E402

# 'PDAH' — auth handshake, sent by the client immediately after connect
# when FLAGS_net_auth_token is set: u32 magic + 16B nonce + 16B
# HMAC-SHA256(token, "hs" + nonce) truncated tag. The server answers one
# byte: 0x01 accepted (both sides then switch to 'PDAR' records), else
# the connection closes and `net.auth_rejects` counts the peer.
AUTH_MAGIC = 0x50444148
# 'PDAR' — one authenticated record: u32 magic + u32 len + 16B
# HMAC-SHA256(token, u64 seq + payload) tag + payload. The per-direction
# sequence number is implicit (both sides count), so replayed or
# reordered records fail the tag check and drop the connection.
AUTH_REC_MAGIC = 0x50444152
# 'PDDL' — OPTIONAL absolute-deadline prefix (FLAGS_net_deadline_wire):
# u32 magic + f64 remaining seconds, sent before a request's first frame
# so the server drops already-expired work (`net.deadline_drops`)
# instead of computing it. Off by default: old peers reject the unknown
# magic, and absence keeps the wire byte-identical to the pre-substrate
# protocols.
DEADLINE_MAGIC = 0x5044444C
_DEADLINE_HEAD = struct.pack("<I", DEADLINE_MAGIC)

# The bus's substrate trace carriage: a length-prefix equal to this
# sentinel (impossible as a real length — lengths are non-negative)
# announces "26-byte trace ctx + u64 real length + payload" instead of
# the legacy convention of appending the ctx as a 6th pickled tuple
# element. Negative 'PDTC', so a hex dump still reads as trace.
BUS_TRACE_SENTINEL = -0x50445443

_TAG_LEN = 16
_AUTH_HELLO_LEN = 4 + 16 + _TAG_LEN
_HANDSHAKE_TIMEOUT_S = 5.0
_AUTH_RECORD_MAX = 1 << 20


def _count(name: str) -> None:
    if _monitor._ENABLED:
        _monitor.count(name)


class AuthError(ConnectionError):
    """Peer failed the 'PDAH' handshake or a 'PDAR' record tag check."""


class DeadlineExpiredError(ConnectionError):
    """A 'PDDL'-carried deadline had already passed when the request
    reached the server: the work is dropped, not computed."""


class ConnectDeadlineError(TimeoutError):
    """RpcChannel.connect ran out of deadline before any endpoint
    answered (distinct from a per-endpoint connect timeout, which feeds
    the round-robin retry instead of aborting the call)."""


# ---- TLS --------------------------------------------------------------------

def _tls_enabled() -> bool:
    return bool(str(_flags.flag("net_tls_cert") or "")
                or str(_flags.flag("net_tls_ca") or ""))


def _tls_wrap(sock, server_side: bool):
    import ssl
    cert = str(_flags.flag("net_tls_cert") or "")
    key = str(_flags.flag("net_tls_key") or "")
    ca = str(_flags.flag("net_tls_ca") or "")
    if server_side:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, key or None)
        if ca:
            ctx.load_verify_locations(ca)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx.wrap_socket(sock, server_side=True)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False   # fleet endpoints are bare IPs
    if ca:
        ctx.load_verify_locations(ca)
        ctx.verify_mode = ssl.CERT_REQUIRED
    else:
        ctx.verify_mode = ssl.CERT_NONE
    if cert:  # mutual TLS when the client also holds a cert
        ctx.load_cert_chain(cert, key or None)
    return ctx.wrap_socket(sock)


# ---- per-frame HMAC auth ----------------------------------------------------

def _auth_token() -> str:
    return str(_flags.flag("net_auth_token") or "")


def _auth_tag(token: bytes, *parts: bytes) -> bytes:
    mac = _hmac_mod.new(token, digestmod=hashlib.sha256)
    for p in parts:
        mac.update(p)
    return mac.digest()[:_TAG_LEN]


class _AuthSocket:
    """Record-layer socket wrapper: every outgoing buffer is chunked into
    'PDAR' records carrying a truncated HMAC-SHA256 over (direction
    sequence + payload); incoming records are verified and re-buffered,
    so the plane codecs' recv()/sendall() calls work unchanged on top.
    A bad tag (tamper, replay, reorder, truncation) raises AuthError and
    the connection drops — never a silently accepted byte."""

    def __init__(self, sock, token: bytes):
        self._sock = sock
        self._token = token
        self._rbuf = bytearray()
        self._send_seq = 0
        self._recv_seq = 0
        self._send_lock = threading.Lock()

    def sendall(self, data) -> None:
        data = bytes(data)
        out = bytearray()
        with self._send_lock:
            for off in range(0, len(data), _AUTH_RECORD_MAX) or (0,):
                chunk = data[off:off + _AUTH_RECORD_MAX]
                seq = struct.pack("<Q", self._send_seq)
                self._send_seq += 1
                out += struct.pack("<II", AUTH_REC_MAGIC, len(chunk))
                out += _auth_tag(self._token, seq, chunk)
                out += chunk
            self._sock.sendall(bytes(out))

    def _fill(self) -> None:
        hdr = recv_exact(self._sock, 8 + _TAG_LEN)
        magic, n = struct.unpack("<II", hdr[:8])
        if magic != AUTH_REC_MAGIC or n > _AUTH_RECORD_MAX:
            _count("net.auth_rejects")
            raise AuthError(f"auth record: bad header 0x{magic:08X}/{n}")
        payload = recv_exact(self._sock, n)
        seq = struct.pack("<Q", self._recv_seq)
        if not _hmac_mod.compare_digest(
                hdr[8:], _auth_tag(self._token, seq, payload)):
            _count("net.auth_rejects")
            raise AuthError("auth record: tag mismatch")
        self._recv_seq += 1
        self._rbuf += payload

    def recv(self, n: int) -> bytes:
        if not self._rbuf:
            self._fill()
        take = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return take

    # the substrate's recv_exact() and the plane codecs only touch this
    # surface; anything else (fileno, getpeername, ...) passes through
    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def gettimeout(self):
        return self._sock.gettimeout()

    def setsockopt(self, *args) -> None:
        self._sock.setsockopt(*args)

    def shutdown(self, how) -> None:
        self._sock.shutdown(how)

    def close(self) -> None:
        self._sock.close()

    def __getattr__(self, name):
        return getattr(self._sock, name)


def secure_client(sock):
    """Apply the one-flag-flip security stack to a freshly connected
    client socket: TLS wrap (FLAGS_net_tls_*), then the 'PDAH' auth
    handshake + 'PDAR' record layer (FLAGS_net_auth_token). With both
    flags off this is the identity — the wire stays byte-identical to
    the pre-substrate protocols."""
    if _tls_enabled():
        sock = _tls_wrap(sock, server_side=False)
    token = _auth_token()
    if token:
        nonce = os.urandom(16)
        tok = token.encode()
        sock.sendall(struct.pack("<I", AUTH_MAGIC) + nonce
                     + _auth_tag(tok, b"hs", nonce))
        ack = recv_exact(sock, 1,
                         time.monotonic() + _HANDSHAKE_TIMEOUT_S)
        if ack != b"\x01":
            raise AuthError("net: server rejected auth handshake")
        sock = _AuthSocket(sock, tok)
    return sock


def secure_server(conn, plane: str = "net"):
    """Server-side mirror of `secure_client` for one accepted
    connection. A peer that fails the TLS handshake or the 'PDAH' check
    is counted (`net.auth_rejects`) and its connection closed — the
    accept loop moves on, the server never serves an unauthenticated
    byte."""
    if _tls_enabled():
        try:
            conn = _tls_wrap(conn, server_side=True)
        except OSError:
            _count("net.auth_rejects")
            _count(f"net.{plane}.auth_rejects")
            try:
                conn.close()
            except OSError:
                pass
            raise AuthError("net: TLS handshake failed") from None
    token = _auth_token()
    if token:
        tok = token.encode()
        ok = False
        try:
            hello = recv_exact(conn, _AUTH_HELLO_LEN,
                               time.monotonic() + _HANDSHAKE_TIMEOUT_S)
            (magic,) = struct.unpack("<I", hello[:4])
            ok = (magic == AUTH_MAGIC and _hmac_mod.compare_digest(
                hello[20:], _auth_tag(tok, b"hs", hello[4:20])))
        except (OSError, ValueError):
            ok = False
        if not ok:
            _count("net.auth_rejects")
            _count(f"net.{plane}.auth_rejects")
            try:
                conn.sendall(b"\x00")
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            raise AuthError("net: client failed auth handshake")
        conn.sendall(b"\x01")
        conn = _AuthSocket(conn, tok)
    return conn


def security_on() -> bool:
    """True when either security flag is flipped (the wire is no longer
    byte-compatible with pre-substrate peers)."""
    return bool(_auth_token()) or _tls_enabled()


# ---- absolute-deadline propagation ------------------------------------------

def deadline_wire_enabled() -> bool:
    return bool(_flags.flag("net_deadline_wire"))


def send_deadline(sock, deadline: float | None) -> None:
    """Prefix the next request with its remaining budget ('PDDL'). The
    wire carries RELATIVE seconds — monotonic clocks do not compare
    across hosts — and the server re-anchors on its own clock."""
    if deadline is None:
        return
    sock.sendall(struct.pack("<Id", DEADLINE_MAGIC,
                             deadline - time.monotonic()))


def recv_head(sock, n: int, deadline: float | None = None,
              plane: str = "net"):
    """Read an n-byte (n >= 4) message head, transparently consuming an
    optional 'PDDL' deadline prefix. Returns `(head, request_deadline)`
    where request_deadline is an absolute monotonic time or None. An
    already-expired deadline raises DeadlineExpiredError after counting
    `net.deadline_drops` — the caller drops the connection's pending
    work instead of computing it."""
    head = recv_exact(sock, 4, deadline)
    req_deadline = None
    while head == _DEADLINE_HEAD:
        (remaining,) = struct.unpack("<d", recv_exact(sock, 8, deadline))
        if remaining <= 0:
            _count("net.deadline_drops")
            _count(f"net.{plane}.deadline_drops")
            raise DeadlineExpiredError(
                f"net: request expired {-remaining:.3f}s before the "
                "server read it")
        req_deadline = time.monotonic() + remaining
        head = recv_exact(sock, 4, deadline)
    if n > 4:
        head += recv_exact(sock, n - 4, deadline)
    return head, req_deadline


# ---- bounded retry with exponential backoff + full jitter -------------------

def _span(span_name):
    if span_name is None:
        return None
    from ..obs import trace as _trace
    return _trace.span(span_name)


def call_with_retry(attempt_fn, *, plane: str = "net", op: str = "call",
                    max_retries: int = 3, backoff_s: float = 0.05,
                    max_backoff_s: float = 2.0,
                    deadline: float | None = None,
                    retry_on=(OSError,), no_retry=(),
                    on_transport_error=None, span_name=None,
                    legacy_retry_counter: str | None = None):
    """THE retry loop (previously hand-rolled per plane): run
    `attempt_fn()`; on a transport failure back off
    `backoff_s * 2^k * (1 + U[0,1))` (full jitter, capped at
    `max_backoff_s`) and retry. With `deadline` (absolute monotonic) the
    budget is the CALL DEADLINE — resolver-backed planes keep retrying
    until failover lands or the deadline expires; otherwise the budget
    is `max_retries` attempts. Exceptions in `no_retry` (application
    errors the peer reported) raise immediately. `on_transport_error`
    runs between attempts (drop the connection, re-resolve endpoints).
    Under FLAGS_trace the WHOLE call is one `span_name` span that closes
    with error status when the call ultimately fails."""
    sp = _span(span_name)
    delay = backoff_s
    last: BaseException | None = None
    try:
        attempt = 0
        while True:
            if attempt:
                _count("net.retries")
                _count(f"net.{plane}.retries")
                if legacy_retry_counter is not None:
                    _count(legacy_retry_counter)
                # full jitter; host RNG is the point — this never traces
                time.sleep(delay * (1.0 + random.random()))  # tpu-lint: disable=stdlib-random
                delay = min(delay * 2, max_backoff_s)
            try:
                out = attempt_fn()
                if sp is not None:
                    sp.end(retries=attempt)
                return out
            except no_retry:
                raise
            except retry_on as e:
                last = e
                if on_transport_error is not None:
                    on_transport_error()
            attempt += 1
            if deadline is not None:
                if time.monotonic() >= deadline:
                    break
            elif attempt > max_retries:
                break
        raise last
    except BaseException as e:
        if sp is not None:  # idempotent: no-op when the success path ran
            from ..obs import trace as _trace
            sp.end(status=_trace.STATUS_ERROR,
                   error=f"{type(e).__name__}: {str(e)[:200]}")
        raise


# ---- client side: RpcChannel ------------------------------------------------

def _parse_endpoint(ep):
    if isinstance(ep, (tuple, list)):
        return str(ep[0]), int(ep[1])
    host, port = str(ep).rsplit(":", 1)
    return host, int(port)


def dial(endpoint, timeout: float | None = None, plane: str = "net"):
    """One-shot secured connection without channel bookkeeping, for
    control-plane exchanges that own their socket's lifetime (HA
    replication tails, one-shot collector queries)."""
    host, port = _parse_endpoint(endpoint)
    s = _socket_mod.create_connection((host, port), timeout=timeout)
    s.setsockopt(_socket_mod.IPPROTO_TCP, _socket_mod.TCP_NODELAY, 1)
    try:
        return secure_client(s)
    except BaseException:
        try:
            s.close()
        except OSError:
            pass
        raise


class RpcChannel:
    """One logical client connection for one plane: owns the socket, the
    resolver hook (PS HA failover / fleet routing re-resolve through
    it), transparent reconnect (counted), the plane's fault sites, and
    the security stack. The plane's verb framing runs THROUGH the
    channel (`sendall` / `recv_exact` / `recv_crc`), so the bytes on the
    wire are exactly the plane's own protocol unless auth/TLS is on.

    Fault sites: `net.<plane>.send` and `net.<plane>.recv` always fire;
    `legacy_sites=(send_site, recv_site)` keeps a plane's historical
    spec grammar working (e.g. `ps.rpc.send`). `torn` specs mangle the
    outgoing payload through either site name.
    """

    def __init__(self, plane: str, resolver=None, endpoint=None,
                 connect_timeout: float = 2.0, nodelay: bool = True,
                 legacy_sites=(None, None),
                 legacy_reconnect_counter: str | None = None,
                 on_connect=None):
        if resolver is None and endpoint is None:
            raise ValueError("RpcChannel needs an endpoint or a resolver")
        self.plane = plane
        self._resolver = resolver
        self._endpoint = endpoint
        self.connect_timeout = connect_timeout
        self._nodelay = nodelay
        self._send_site, self._recv_site = legacy_sites
        self._legacy_reconnect_counter = legacy_reconnect_counter
        self._on_connect = on_connect
        self._sock = None
        self._connected_once = False

    # -- connection ownership --
    def endpoints(self):
        if self._resolver is not None:
            eps = self._resolver()
            return [eps] if isinstance(eps, (str, tuple)) else list(eps)
        return [self._endpoint]

    @property
    def endpoint(self):
        return self._endpoint

    @endpoint.setter
    def endpoint(self, ep):
        if ep != self._endpoint:
            self.drop()
        self._endpoint = ep

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def connect(self, deadline: float | None = None):
        """Connect (or return the cached connection) to the first
        reachable resolved endpoint, apply TCP_NODELAY + the security
        stack, and count a reconnect when this channel had a connection
        before. Raises the last endpoint's error when none answers, or
        ConnectDeadlineError when an absolute `deadline` expires first."""
        if self._sock is not None:
            return self._sock
        last: BaseException | None = None
        for ep in self.endpoints():
            host, port = _parse_endpoint(ep)
            ct = self.connect_timeout
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectDeadlineError(
                        "connect deadline exceeded") from last
                ct = min(ct, remaining)
            try:
                s = _socket_mod.create_connection((host, port), timeout=ct)
            except OSError as e:
                last = e
                continue
            s.setsockopt(_socket_mod.IPPROTO_TCP,
                         _socket_mod.TCP_NODELAY, 1)
            try:
                s = secure_client(s)
            except (OSError, ValueError) as e:
                try:
                    s.close()
                except OSError:
                    pass
                last = e
                continue
            if self._connected_once:
                _count("net.reconnects")
                _count(f"net.{self.plane}.reconnects")
                if self._legacy_reconnect_counter is not None:
                    _count(self._legacy_reconnect_counter)
            self._connected_once = True
            self._sock = s
            self._endpoint = ep  # tpu-lint: disable=buffer-retain
            if self._on_connect is not None:
                self._on_connect(self)
            return s
        raise last if last is not None else ConnectionError(
            f"net.{self.plane}: no endpoint resolved")

    def drop(self) -> None:
        """Forget the connection so the next request starts clean."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    close = drop

    # -- channel I/O (the plane codecs call these) --
    def check_send_faults(self, data=None):
        """Fire this channel's send fault sites; `torn` specs mangle and
        return the payload."""
        if _faults._ENABLED:
            _faults.check(f"net.{self.plane}.send")
            if data is not None:
                data = _faults.mangle(f"net.{self.plane}.send", data)
            if self._send_site is not None:
                _faults.check(self._send_site)
                if data is not None:
                    data = _faults.mangle(self._send_site, data)
        return data

    def check_recv_faults(self) -> None:
        if _faults._ENABLED:
            _faults.check(f"net.{self.plane}.recv")
            if self._recv_site is not None:
                _faults.check(self._recv_site)

    def sendall(self, data, deadline: float | None = None) -> None:
        data = self.check_send_faults(data)
        sock = self.connect()
        if deadline is not None and deadline_wire_enabled():
            send_deadline(sock, deadline)
        sock.sendall(data)

    def send_frames(self, frames, deadline: float | None = None) -> None:
        """Scatter-gather `sendall`: identical wire bytes, no join copy
        on the fault-free plain-TCP path. With fault injection armed the
        frames are joined first so `torn` mangling keeps its documented
        truncate-the-whole-payload semantics."""
        if _faults._ENABLED:
            self.sendall(b"".join(
                bytes(f) if not isinstance(f, (bytes, bytearray, memoryview))
                else f for f in frames), deadline)
            return
        sock = self.connect()
        if deadline is not None and deadline_wire_enabled():
            send_deadline(sock, deadline)
        send_frames(sock, frames)

    def recv_exact(self, n: int, deadline: float | None = None) -> bytes:
        self.check_recv_faults()
        return recv_exact(self.connect(), n, deadline)

    def recv_crc(self, expect_magic: int,
                 deadline: float | None = None) -> bytes:
        self.check_recv_faults()
        return recv_crc_frame(self.connect(), expect_magic, deadline)

    @property
    def sock(self):
        return self.connect()

    # -- retries --
    def call(self, attempt_fn, *, op: str = "call",
             max_retries: int = 3, backoff_s: float = 0.05,
             deadline: float | None = None, no_retry=(),
             span_name=None, legacy_retry_counter: str | None = None,
             on_transport_error=None):
        """Run `attempt_fn()` under the substrate retry loop; transport
        failures drop this channel's connection (so the next attempt
        reconnects, possibly at a re-resolved endpoint) before the
        caller's own `on_transport_error` hook runs."""
        def _on_err():
            self.drop()
            if on_transport_error is not None:
                on_transport_error()

        return call_with_retry(
            attempt_fn, plane=self.plane, op=op, max_retries=max_retries,
            backoff_s=backoff_s, deadline=deadline, no_retry=no_retry,
            span_name=span_name, legacy_retry_counter=legacy_retry_counter,
            on_transport_error=_on_err)


# ---- server side: RpcServer -------------------------------------------------

def make_listener(host: str, port: int, backlog: int = 64):
    """One implementation of listener setup (SO_REUSEADDR, bind, listen)
    for the planes that keep a bespoke accept loop."""
    sock = _socket_mod.socket(_socket_mod.AF_INET,
                              _socket_mod.SOCK_STREAM)
    sock.setsockopt(_socket_mod.SOL_SOCKET, _socket_mod.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(backlog)
    return sock


class RpcServer:
    """Accept-loop harness for one plane's server: owns the listener,
    polls accept with a timeout (so stop() is prompt), applies the
    security stack to every accepted connection (rejecting + counting
    unauthenticated peers), tracks live connections so stop() can close
    them out from under blocked reads, and runs the plane's
    `handler(conn, addr)` on a daemon thread per connection."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0,
                 plane: str = "net", backlog: int = 64,
                 poll_s: float = 0.2, name: str | None = None):
        self._handler = handler
        self.plane = plane
        self._poll_s = poll_s
        self._name = name or f"net-{plane}"
        self._listener = make_listener(host, port, backlog)
        self.host, self.port = self._listener.getsockname()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._conns: "weakref.WeakSet" = weakref.WeakSet()
        self._listener_closed = False

    def start(self) -> "RpcServer":
        self._thread = _syncwatch.Thread(
            target=self._accept_loop, daemon=True, name=self._name)
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        self._listener.settimeout(self._poll_s)
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except _socket_mod.timeout:
                continue
            except OSError:
                return   # listener closed (drain/stop)
            try:
                conn = secure_server(conn, self.plane)
            except (AuthError, OSError, ValueError):
                continue  # counted in secure_server; peer is gone
            self._conns.add(conn)
            _syncwatch.Thread(target=self._run_handler, args=(conn, addr),
                             daemon=True,
                             name=f"{self._name}-conn").start()

    def _run_handler(self, conn, addr) -> None:
        try:
            self._handler(conn, addr)
        except (OSError, ValueError):
            pass   # connection-scoped failure: the server stays up
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close_listener(self) -> None:
        """Stop accepting (the port closes NOW — fleet drain semantics)
        while existing connections keep being served."""
        if self._listener_closed:
            return
        self._listener_closed = True
        try:
            self._listener.shutdown(_socket_mod.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stop.set()
        self.close_listener()
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
