"""Shared socket framing helpers (used by the PS RPC plane and the
inference C-API server — one implementation of exact-read)."""
from __future__ import annotations


def recv_exact(sock, n: int) -> bytes:
    if n < 0:
        raise ValueError(f"recv_exact: negative length {n}")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)
