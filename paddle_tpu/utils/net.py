"""Shared socket framing helpers (used by the PS RPC plane and the
inference C-API server — one implementation of exact-read, plus the
inference response status frame).

Inference response statuses (csrc/predict_capi.cpp mirrors these): a
client must be able to tell backpressure (retryable, the server is
healthy) from failure — overload and deadline expiry get their own codes
instead of riding the generic error status.
"""
from __future__ import annotations

import struct

# response status byte of the inference wire protocol
STATUS_OK = 0            # payload: u32 n_tensors + tensors
STATUS_ERROR = 1         # payload: u32 len + utf-8 message
STATUS_OVERLOADED = 2    # payload: u32 len + message; retry with backoff
STATUS_DEADLINE = 3      # payload: u32 len + message; request expired

_RESP_MAGIC = 0x50445253  # 'PDRS'


def send_status_frame(sock, status: int, msg: bytes | str = b"") -> None:
    """Send a non-OK inference response frame: magic + status + message.
    One implementation so the server cannot desynchronize the stream by
    hand-rolling a frame per call site."""
    if isinstance(msg, str):
        msg = msg.encode()
    sock.sendall(struct.pack("<IB", _RESP_MAGIC, status)
                 + struct.pack("<I", len(msg)) + msg)


def recv_exact(sock, n: int) -> bytes:
    if n < 0:
        raise ValueError(f"recv_exact: negative length {n}")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)
