"""Filesystem wrappers: LocalFS + HDFS client surface.

Reference parity: `paddle/fluid/framework/io/fs.cc` / python
`fluid/incubate/fleet/utils/fs.py` (LocalFS, HDFSClient with
ls_dir/is_file/mkdirs/delete/mv/upload/download) — used by distributed
checkpointing and dataset ingestion.

TPU-native note: checkpoints here are local/NFS paths (sharded_io);
HDFSClient keeps the API shape and shells out to a configured `hadoop`
binary when one exists, so PS-era ingest scripts port unchanged on hosts
that have the client installed.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Tuple


class LocalFS:
    def ls_dir(self, path) -> Tuple[List[str], List[str]]:
        """(dirs, files) — reference LocalFS::ls_dir split."""
        if not os.path.exists(path):
            return [], []
        entries = sorted(os.listdir(path))
        dirs = [e for e in entries if os.path.isdir(os.path.join(path, e))]
        files = [e for e in entries if not os.path.isdir(os.path.join(path, e))]
        return dirs, files

    def is_exist(self, path) -> bool:
        return os.path.exists(path)

    def is_file(self, path) -> bool:
        return os.path.isfile(path)

    def is_dir(self, path) -> bool:
        return os.path.isdir(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False):
        if os.path.exists(dst):
            if not overwrite:
                raise FileExistsError(dst)
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient:
    """HDFS surface over the `hadoop fs` CLI (fs.cc shells out the same
    way); raises a clear error when no hadoop binary is configured."""

    def __init__(self, hadoop_home=None, configs=None):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else shutil.which("hadoop")
        self._configs = configs or {}

    def _run_raw(self, *args):
        """CompletedProcess from `hadoop fs <args>`; infra failures (no
        binary, hang) become RuntimeError uniformly."""
        if not self._hadoop:
            raise RuntimeError(
                "HDFSClient: no hadoop binary found — set hadoop_home or "
                "install the client (LocalFS covers local checkpoints)")
        cfg = []
        for k, v in self._configs.items():
            cfg += ["-D", f"{k}={v}"]
        try:
            return subprocess.run([self._hadoop, "fs"] + cfg + list(args),
                                  capture_output=True, text=True, timeout=300)
        except FileNotFoundError as e:
            raise RuntimeError(
                f"HDFSClient: hadoop binary not runnable: {self._hadoop}"
            ) from e
        except subprocess.TimeoutExpired as e:
            raise RuntimeError(
                f"HDFSClient: hadoop fs {' '.join(args)} timed out") from e

    def _run(self, *args) -> str:
        r = self._run_raw(*args)
        if r.returncode != 0:
            raise RuntimeError(f"hadoop fs {' '.join(args)}: {r.stderr[:400]}")
        return r.stdout

    def _test(self, flag, path) -> bool:
        # `-test` exits 1 for "no" — every OTHER failure (auth, namenode
        # down) must propagate, not read as "path absent"
        r = self._run_raw("-test", flag, path)
        if r.returncode == 0:
            return True
        if r.returncode == 1 and not r.stderr.strip():
            return False
        raise RuntimeError(f"hadoop fs -test {flag}: {r.stderr[:400]}")

    def is_exist(self, path) -> bool:
        return self._test("-e", path)

    def is_file(self, path) -> bool:
        return self._test("-f", path)

    def is_dir(self, path) -> bool:
        return self._test("-d", path)

    def ls_dir(self, path):
        """(dirs, files) as BASENAMES — same contract as LocalFS.ls_dir
        (split on the 8th field so names with spaces survive)."""
        out = self._run("-ls", path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split(None, 7)
            if len(parts) < 8 or parts[0].startswith("Found"):
                continue
            name = os.path.basename(parts[7].rstrip("/"))
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        self._run("-mv", src, dst)

    def touch(self, path, exist_ok=True):
        if not exist_ok and self.is_exist(path):
            raise FileExistsError(path)
        self._run("-touchz", path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)
