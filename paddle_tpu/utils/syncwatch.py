"""Runtime concurrency sanitizer + central thread registry.

The repo is a genuinely concurrent system — watchdog runners, PS
replication tails, fleet health probes, telemetry accept/conn threads,
the autoscaler loop — and a wedged thread on a pod surfaces only as an
opaque rc=124. Two always-available primitives fix the observability
half and, under a flag, the correctness half:

  - `ThreadRegistry` (always on): every framework thread is spawned via
    `syncwatch.Thread(..., owner=__name__)`, which records name, owner
    module, daemonhood, and the SPAWN STACK. The conftest leak fixtures
    collapse onto it, and `python -m paddle_tpu.monitor threads` renders
    the live table. Registration is one dict insert per spawn — spawning
    a thread is never a hot path.

  - lock-order sanitizer (`FLAGS_sync_watch`): `syncwatch.lock(name)` /
    `rlock(name)` hand out watched wrappers recording per-thread
    held-sets + acquisition stacks and maintaining the observed
    lock-order graph (edge A->B = "B acquired while holding A"). An
    acquisition that would close a cycle raises `SyncOrderError` naming
    BOTH stacks — the current one and the first-observed stack of the
    reverse path — BEFORE blocking on the real lock, so a seeded
    deadlock reports instead of wedging (`FLAGS_sync_order_fatal=False`
    downgrades to a warning + `sync.order_violations` counter for
    soaks). Hold times land in the `sync.lock_hold_ms` histogram;
    holds over `FLAGS_sync_hold_warn_ms` warn with the acquisition
    stack. Disabled (default) the factories return PLAIN threading
    locks: one module-attribute check at construction, zero per-acquire
    cost (the PR-1 overhead-guard contract).

Same-name edges are never recorded: multiple instances sharing one name
(e.g. the PS client's per-shard locks) are an ordered same-class
acquisition whose protocol — ascending shard order — is the caller's,
and a self-loop would be a guaranteed false cycle.

The static half of this plane is `analysis/concurrency.py` (tpu-lint
level 4), which builds the same graph from the AST at review time.
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
import warnings
import weakref
from typing import Any, Dict, List, Optional

from ..core import flags as _flags

__all__ = ["SyncOrderError", "Thread", "lock", "rlock", "live_threads",
           "dump_sync", "render_threads", "order_edges", "violations"]

# hot-path gate (faults/monitor/analysis pattern): factories read this
# module attribute; watch_flag keeps it in sync with set_flags
_ENABLED: bool = bool(_flags.flag("sync_watch"))


def _on_flag(value) -> None:
    global _ENABLED
    _ENABLED = bool(value)


_flags.watch_flag("sync_watch", _on_flag)


def enabled() -> bool:
    return _ENABLED


class SyncOrderError(RuntimeError):
    """A lock acquisition would close a cycle in the observed lock-order
    graph — the canonical ingredients of a deadlock. `.cycle` is the
    node path; the message carries both acquisition stacks."""

    def __init__(self, message: str, cycle: List[str]):
        super().__init__(message)
        self.cycle = cycle


# ---------------------------------------------------------------------------
# thread registry (always on)
# ---------------------------------------------------------------------------

_REG_LOCK = threading.Lock()
# id(thread) -> {"ref": weakref, "owner": str, "spawned": str, "t0": float}
_REGISTRY: Dict[int, Dict[str, Any]] = {}


class Thread(threading.Thread):
    """`threading.Thread` that self-registers in the central registry.

    `owner` names the spawning module; when omitted it is inferred from
    the caller's frame, so the leak report reads "obs.telemetry leaked
    telemetry-accept", not a bare thread name. The spawn stack is
    captured at CONSTRUCTION — that is the site a leak report must
    point at, not the run() frame."""

    def __init__(self, *args, owner: Optional[str] = None, **kwargs):
        super().__init__(*args, **kwargs)
        if owner is None:
            import sys
            owner = sys._getframe(1).f_globals.get("__name__", "?")
        self.owner = owner
        spawned = "".join(traceback.format_stack(limit=8)[:-1])
        with _REG_LOCK:
            _REGISTRY[id(self)] = {
                "ref": weakref.ref(self), "owner": owner,
                "spawned": spawned, "t0": time.time()}
            if len(_REGISTRY) > 512:
                _prune_registry_locked()


def _prune_registry_locked() -> None:
    dead = [k for k, row in _REGISTRY.items()
            if (t := row["ref"]()) is None or
            (t._started.is_set() and not t.is_alive())]
    for k in dead:
        _REGISTRY.pop(k, None)


def live_threads() -> List[Dict[str, Any]]:
    """Rows for every ALIVE registered thread: name, owner module, age,
    daemonhood, spawn stack, and (sanitizer on) currently-held locks
    with their hold ages and acquisition stacks."""
    now = time.time()
    with _REG_LOCK:
        _prune_registry_locked()
        rows = []
        for row in _REGISTRY.values():
            t = row["ref"]()
            if t is None or not t.is_alive():
                continue
            rows.append({"name": t.name, "owner": row["owner"],
                         "daemon": t.daemon, "ident": t.ident,
                         "age_s": round(now - row["t0"], 3),
                         "spawned": row["spawned"]})
    with _STATE_LOCK:
        held = {ident: [{"lock": h[0],
                         "held_ms": round((now - h[1]) * 1e3, 3),
                         "stack": _format_stack(h[2])}
                        for h in holds]
                for ident, holds in _HELD.items() if holds}
    for r in rows:
        r["held"] = held.get(r["ident"], [])
    return sorted(rows, key=lambda r: (r["owner"], r["name"]))


# ---------------------------------------------------------------------------
# lock-order sanitizer (FLAGS_sync_watch)
# ---------------------------------------------------------------------------

_STATE_LOCK = threading.Lock()          # plain: guards the books below
# thread ident -> [(lock name, t_acquire, acquisition stack), ...]
_HELD: Dict[int, List[tuple]] = {}
# src name -> {dst name -> {"stack_src","stack_dst","thread","count"}}:
# edge src->dst = "dst acquired while holding src", first-observed stacks
_EDGES: Dict[str, Dict[str, Dict[str, Any]]] = {}
_VIOLATIONS: int = 0


def violations() -> int:
    return _VIOLATIONS


def order_edges() -> Dict[str, List[str]]:
    """Adjacency snapshot of the observed lock-order graph."""
    with _STATE_LOCK:
        return {src: sorted(dsts) for src, dsts in _EDGES.items()}


def _find_path_locked(src: str, dst: str) -> Optional[List[str]]:
    """DFS: a path src ~> dst in the edge graph (callers hold
    _STATE_LOCK)."""
    stack, seen = [(src, [src])], {src}
    while stack:
        node, path = stack.pop()
        for nxt in _EDGES.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _stack_here(skip: int = 3, limit: int = 10):
    """Cheap per-acquire stack capture: (file, line, func) tuples from a
    raw frame walk — NO source-line reads, those happen lazily in
    `_format_stack` only when a violation/warning/render needs the text.
    `traceback.format_stack` here costs ~100x more and alone blows the
    <=2% serving-p99 budget of the enabled path (BENCH_SYNC=ab)."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    frames = []
    while f is not None and len(frames) < limit:
        frames.append((f.f_code.co_filename, f.f_lineno,
                       f.f_code.co_name))
        f = f.f_back
    return tuple(reversed(frames))


def _format_stack(frames) -> str:
    """Render a `_stack_here` capture in traceback style (cold path)."""
    if isinstance(frames, str):     # dump round-trip: already text
        return frames
    import linecache
    lines = []
    for fname, lineno, func in frames:
        lines.append(f'  File "{fname}", line {lineno}, in {func}\n')
        src = linecache.getline(fname, lineno).strip()
        if src:
            lines.append(f"    {src}\n")
    return "".join(lines)


class _WatchedLock:
    """Wrapper over a real threading lock. On acquire: cycle-check the
    would-be edges BEFORE blocking, then record edges + the hold; on
    release: pop the hold and feed the hold-time histogram/warning.
    RLock re-entry only does the bookkeeping on the OUTERMOST
    acquire/release."""

    __slots__ = ("_real", "name", "_reentrant", "_depth")

    def __init__(self, real, name: str, reentrant: bool = False):
        self._real = real
        self.name = name
        self._reentrant = reentrant
        self._depth = threading.local()

    # -- bookkeeping --
    def _check_and_record(self) -> None:
        global _VIOLATIONS
        ident = threading.get_ident()
        stack = _stack_here()
        cycle = None
        with _STATE_LOCK:
            holds = _HELD.setdefault(ident, [])
            for hname, _t0, hstack in holds:
                if hname == self.name:
                    continue        # same-name class: caller's protocol
                # acquiring self while holding hname adds hname->self;
                # a path self ~> hname means that edge closes a cycle
                path = _find_path_locked(self.name, hname)
                if path is not None:
                    first = _EDGES[path[0]][path[1]]
                    cycle = (path, hname, hstack, stack, first)
                    break
            if cycle is None:
                for hname, _t0, hstack in holds:
                    if hname == self.name:
                        continue
                    e = _EDGES.setdefault(hname, {}).get(self.name)
                    if e is None:
                        _EDGES[hname][self.name] = {
                            "stack_src": hstack, "stack_dst": stack,
                            "thread": threading.current_thread().name,
                            "count": 1}
                    else:
                        e["count"] += 1
                holds.append((self.name, time.monotonic(), stack))
                return
            _VIOLATIONS += 1
        path, hname, hstack, stack, first = cycle
        loop = " -> ".join(path + ["(held)"])
        msg = (f"lock-order cycle: acquiring '{self.name}' while holding "
               f"'{hname}' inverts the established order {loop}\n"
               f"--- this acquisition (thread "
               f"{threading.current_thread().name!r}, already holding "
               f"'{hname}'):\n{_format_stack(stack)}"
               f"--- established '{path[0]}' -> '{path[1]}' first "
               f"observed (thread {first['thread']!r}):\n"
               f"{_format_stack(first['stack_dst'])}")
        from .. import monitor as _monitor
        if _monitor._ENABLED:
            _monitor.count("sync.order_violations")
        if bool(_flags.flag("sync_order_fatal")):
            raise SyncOrderError(msg, path)
        warnings.warn(f"syncwatch: {msg}", stacklevel=3)
        with _STATE_LOCK:
            _HELD.setdefault(ident, []).append(
                (self.name, time.monotonic(), stack))

    def _pop_hold(self) -> None:
        ident = threading.get_ident()
        with _STATE_LOCK:
            holds = _HELD.get(ident, [])
            for i in range(len(holds) - 1, -1, -1):
                if holds[i][0] == self.name:
                    _name, t0, stack = holds.pop(i)
                    break
            else:
                return
        held_ms = (time.monotonic() - t0) * 1e3
        from .. import monitor as _monitor
        if _monitor._ENABLED:
            _monitor.observe("sync.lock_hold_ms", held_ms)
        warn_ms = float(_flags.flag("sync_hold_warn_ms"))
        if warn_ms > 0 and held_ms > warn_ms:
            if _monitor._ENABLED:
                _monitor.count("sync.hold_warns")
            warnings.warn(
                f"syncwatch: '{self.name}' held {held_ms:.1f}ms "
                f"(> FLAGS_sync_hold_warn_ms={warn_ms:g}) by thread "
                f"{threading.current_thread().name!r}; acquired at:\n"
                f"{_format_stack(stack)}", stacklevel=3)

    def _enter_depth(self) -> int:
        d = getattr(self._depth, "n", 0)
        self._depth.n = d + 1
        return d

    def _exit_depth(self) -> int:
        d = getattr(self._depth, "n", 1) - 1
        self._depth.n = d
        return d

    # -- lock protocol --
    def acquire(self, blocking: bool = True, timeout: float = -1):
        outermost = not self._reentrant or self._enter_depth() == 0
        if outermost:
            try:
                self._check_and_record()
            except SyncOrderError:
                if self._reentrant:
                    self._exit_depth()
                raise
        got = self._real.acquire(blocking, timeout)
        if outermost and not got:
            self._pop_hold()
        return got

    def release(self):
        self._real.release()
        if not self._reentrant or self._exit_depth() == 0:
            self._pop_hold()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked()

    def __repr__(self):
        return f"<syncwatch.{'RLock' if self._reentrant else 'Lock'} " \
               f"{self.name!r}>"


def lock(name: str):
    """Factory adopted by the threaded modules: a watched Lock under
    FLAGS_sync_watch, a plain `threading.Lock()` otherwise (zero
    per-acquire cost on the disabled path)."""
    if _ENABLED:
        return _WatchedLock(threading.Lock(), name)
    return threading.Lock()


def rlock(name: str):
    if _ENABLED:
        return _WatchedLock(threading.RLock(), name, reentrant=True)
    return threading.RLock()


# ---------------------------------------------------------------------------
# dump / render (flight-recorder `sync` section, `monitor threads` CLI)
# ---------------------------------------------------------------------------

def dump_sync() -> Dict[str, Any]:
    """The flight-recorder `sync` section (schema /5): the live thread
    table, the observed lock-order graph, and the violation count."""
    with _STATE_LOCK:
        edges = [{"src": src, "dst": dst, "count": e["count"],
                  "thread": e["thread"]}
                 for src, dsts in _EDGES.items()
                 for dst, e in dsts.items()]
        nviol = _VIOLATIONS
    threads = [{k: r[k] for k in
                ("name", "owner", "daemon", "age_s")} |
               {"held": [{"lock": h["lock"], "held_ms": h["held_ms"]}
                         for h in r["held"]]}
               for r in live_threads()]
    return {"enabled": _ENABLED, "threads": threads,
            "lock_order": sorted(edges,
                                 key=lambda e: (e["src"], e["dst"])),
            "violations": nviol}


def render_threads(doc: Optional[Dict[str, Any]] = None,
                   hold_warn_ms: Optional[float] = None) -> str:
    """Text table for `python -m paddle_tpu.monitor threads`: live
    registry (doc=None) or a dump's `sync` section. Threads holding a
    lock longer than `hold_warn_ms` get their acquisition stack dumped
    under the table."""
    live = doc is None
    rows = live_threads() if live else (doc.get("threads") or [])
    if hold_warn_ms is None:
        hold_warn_ms = float(_flags.flag("sync_hold_warn_ms")) or 1e12
    lines = ["-" * 78,
             f"{'thread':<24}{'owner':<28}{'age':>8}{'daemon':>7}  held",
             "-" * 78]
    stuck = []
    for r in rows:
        held = ", ".join(f"{h['lock']}({h['held_ms']:.0f}ms)"
                         for h in (r.get("held") or [])) or "-"
        age = r.get("age_s", 0.0)
        age_s = f"{age / 3600:.1f}h" if age >= 3600 else f"{age:.1f}s"
        lines.append(f"{r['name'][:23]:<24}{r['owner'][:27]:<28}"
                     f"{age_s:>8}{'yes' if r.get('daemon') else 'no':>7}"
                     f"  {held}")
        for h in (r.get("held") or []):
            if h["held_ms"] > hold_warn_ms and h.get("stack"):
                stuck.append((r["name"], h))
    if not rows:
        lines.append("(no registered threads alive)")
    edges = None if live else (doc.get("lock_order") or [])
    if edges is None:
        edges = [{"src": s, "dst": d, "count": None}
                 for s, ds in order_edges().items() for d in ds]
    if edges:
        lines.append("observed lock order (held -> acquired):")
        for e in edges:
            n = f" x{e['count']}" if e.get("count") else ""
            lines.append(f"  {e['src']} -> {e['dst']}{n}")
    if doc is not None and doc.get("violations"):
        lines.append(f"ORDER VIOLATIONS: {doc['violations']}")
    for name, h in stuck:
        lines.append(f"thread {name!r} holding '{h['lock']}' for "
                     f"{h['held_ms']:.0f}ms (> {hold_warn_ms:g}ms), "
                     f"acquired at:")
        lines.extend("  " + ln for ln in h["stack"].splitlines())
    lines.append("-" * 78)
    return "\n".join(lines)


def _reset() -> None:
    """Test hook: forget the observed order graph, held-sets, and the
    violation count (the thread registry survives — it is state about
    real threads, not about the sanitizer)."""
    global _VIOLATIONS
    with _STATE_LOCK:
        _HELD.clear()
        _EDGES.clear()
        _VIOLATIONS = 0
