"""paddle.utils parity: deprecated decorator, unique_name, download stub,
cpp_extension pointer, try_import."""
from __future__ import annotations

import functools
import importlib
import warnings


def deprecated(update_to="", since="", reason="", level=0):
    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(f"{func.__name__} is deprecated since {since}: {reason}. "
                          f"Use {update_to} instead.", DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is required but not installed")


class _UniqueNameGenerator:
    def __init__(self):
        self.ids = {}

    def __call__(self, key):
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return f"{key}_{n}"


generate = _UniqueNameGenerator()


class unique_name:
    _gen = _UniqueNameGenerator()

    @classmethod
    def generate(cls, key):
        return cls._gen(key)


def run_check():
    """paddle.utils.run_check parity: verify the TPU stack works."""
    import jax
    import jax.numpy as jnp
    n = jax.device_count()
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    backend = jax.default_backend()
    print(f"paddle_tpu is installed successfully! backend={backend}, devices={n}, "
          f"matmul checksum={float(y.sum()):.0f}")
    return True


def download(url, path=None, md5sum=None):
    raise RuntimeError("zero-egress environment: datasets must be local "
                       "(use paddle_tpu.vision.datasets with mode='synthetic')")


def require_version(min_version, max_version=None):
    """Check the installed framework version against a range
    (`paddle.utils.require_version`)."""
    from ..framework.version import FRAMEWORK_VERSION as _v

    def parse(s):
        return [int(x) for x in str(s).replace("rc", ".").split(".")[:3]
                if x.isdigit()]

    cur = parse(_v)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {_v} < required min {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {_v} > allowed max {max_version}")
    return True
