"""Custom-operator plugin: runtime-compiled C++ ops + python/Pallas ops.

Reference parity: `paddle/fluid/framework/custom_operator.cc:1` (PD_BUILD_OP
runtime registration) + `python/paddle/utils/cpp_extension/` (JIT-compile
user C++ into a loadable op library).

TPU-native redesign: there is no per-device kernel ABI to plug into — the
compute path is XLA. A custom op is therefore either
  (a) a PYTHON/Pallas function registered with `register_custom_op`
      (autograd via the tape / custom_vjp; jit-traceable directly), or
  (b) a HOST C++ function compiled by `load()` and invoked through
      `jax.pure_callback`, so it composes with jit/vmap at the cost of a
      device→host→device hop (the honest TPU equivalent of a CPU custom
      kernel in the reference).
C ABI for (b): `void <name>(const <T>* x, <T>* y, int64_t n)` elementwise,
optionally `<name>_grad(const <T>* x, const <T>* gy, <T>* gx, int64_t n)`.
"""
from __future__ import annotations

import ctypes
import functools
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, Dict, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op

_REGISTRY: Dict[str, Callable] = {}


# ---------------- (a) python / pallas custom ops ----------------
def register_custom_op(name: str, forward: Callable,
                       backward: Optional[Callable] = None):
    """Register `forward(*arrays) -> array` as op `name`.

    With `backward(residual_inputs, grad_out) -> tuple(grads)` supplied, the
    op gets a custom VJP; otherwise JAX differentiates through `forward`.
    The op is callable from the returned handle, `get_custom_op(name)`, and
    participates in the eager tape and jit tracing like any built-in.
    """
    if backward is not None:
        core = jax.custom_vjp(forward)
        core.defvjp(lambda *xs: (forward(*xs), xs),
                    lambda res, g: tuple(backward(res, g)))
    else:
        core = forward

    def op(*tensors):
        ts = [ensure_tensor(t) for t in tensors]
        return run_op(lambda *arrs: core(*arrs), ts, name)

    op.__name__ = name
    _REGISTRY[name] = op
    return op


def get_custom_op(name: str) -> Callable:
    return _REGISTRY[name]


# ---------------- (b) runtime-compiled C++ host ops ----------------
_HEADER = """\
#include <cstdint>
#define PT_EXPORT extern "C" __attribute__((visibility("default")))
"""

_CTYPE = {np.float32: ctypes.c_float, np.float64: ctypes.c_double,
          np.int32: ctypes.c_int32}


class CppExtensionModule:
    """Handle over a compiled user library: each exported op becomes a
    Tensor-level callable with jit support (pure_callback)."""

    def __init__(self, lib_path: str, functions: Sequence[str],
                 dtype=np.float32):
        self._lib = ctypes.CDLL(lib_path)
        self.lib_path = lib_path
        ct = _CTYPE[dtype]
        self._np_dtype = np.dtype(dtype)
        for fname in functions:
            cfunc = getattr(self._lib, fname)
            cfunc.restype = None
            cfunc.argtypes = [ctypes.POINTER(ct), ctypes.POINTER(ct),
                              ctypes.c_int64]
            gfunc = getattr(self._lib, fname + "_grad", None)
            if gfunc is not None:
                gfunc.restype = None
                gfunc.argtypes = [ctypes.POINTER(ct), ctypes.POINTER(ct),
                                  ctypes.POINTER(ct), ctypes.c_int64]
            setattr(self, fname, self._make_op(fname, cfunc, gfunc, ct))

    def _make_op(self, name, cfunc, gfunc, ct):
        npdt = self._np_dtype

        def host_fwd(x):
            x = np.ascontiguousarray(x, npdt)
            y = np.empty_like(x)
            cfunc(x.ctypes.data_as(ctypes.POINTER(ct)),
                  y.ctypes.data_as(ctypes.POINTER(ct)), x.size)
            return y

        def fwd_cb(a):
            return jax.pure_callback(
                host_fwd, jax.ShapeDtypeStruct(a.shape, npdt), a,
                vmap_method="sequential")

        if gfunc is not None:
            def host_bwd(x, gy):
                x = np.ascontiguousarray(x, npdt)
                gy = np.ascontiguousarray(gy, npdt)
                gx = np.empty_like(x)
                gfunc(x.ctypes.data_as(ctypes.POINTER(ct)),
                      gy.ctypes.data_as(ctypes.POINTER(ct)),
                      gx.ctypes.data_as(ctypes.POINTER(ct)), x.size)
                return gx

            @jax.custom_vjp
            def core(a):
                return fwd_cb(a)

            core.defvjp(
                lambda a: (fwd_cb(a), a),
                lambda res, g: (jax.pure_callback(
                    host_bwd, jax.ShapeDtypeStruct(res.shape, npdt),
                    res, g, vmap_method="sequential"),))
        else:
            core = fwd_cb

        def op(t):
            return run_op(core, [ensure_tensor(t)], f"custom::{name}")

        op.__name__ = name
        _REGISTRY[name] = op
        return op


def load(name: str, sources: Sequence[str], functions: Sequence[str],
         extra_cflags: Sequence[str] = (), build_directory: Optional[str] = None,
         dtype=np.float32, verbose: bool = False) -> CppExtensionModule:
    """JIT-compile user C++ sources into a custom-op library and load it.

    (cpp_extension.load parity; `functions` lists the exported op symbols.)
    Recompiles only when source content changes (content-hash key).
    """
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    h = hashlib.sha256()
    srcs = []
    for s in sources:
        with open(s, "rb") as f:
            data = f.read()
        h.update(data)
        srcs.append(s)
    lib_path = os.path.join(build_dir, f"{name}_{h.hexdigest()[:12]}.so")
    if not os.path.exists(lib_path):
        hdr = os.path.join(build_dir, "paddle_tpu_ext.h")
        with open(hdr, "w") as f:
            f.write(_HEADER)
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
               f"-I{build_dir}", "-o", lib_path, *extra_cflags, *srcs]
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if verbose:
            print(" ".join(cmd), r.stderr, sep="\n")
        if r.returncode != 0:
            raise RuntimeError(f"cpp_extension build failed:\n{r.stderr}")
    return CppExtensionModule(lib_path, functions, dtype=dtype)


class CppExtension:
    """setup()-style descriptor (API-parity shim over `load`)."""

    def __init__(self, sources, name=None, extra_compile_args=()):
        self.sources = list(sources)
        self.name = name
        self.extra_compile_args = list(extra_compile_args)


def setup(name: str, ext_modules, functions: Sequence[str] = (), **kwargs):
    ext = ext_modules[0] if isinstance(ext_modules, (list, tuple)) else ext_modules
    return load(name, ext.sources, functions or [name],
                extra_cflags=ext.extra_compile_args)
