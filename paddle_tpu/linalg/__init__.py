"""paddle.linalg namespace parity — re-exports the linalg op surface."""
from ..ops.linalg import *  # noqa: F401,F403
from ..ops.linalg import (  # noqa: F401
    norm, vector_norm, dist, cond, inv, pinv, det, slogdet, cholesky,
    cholesky_solve, solve, triangular_solve, lstsq, qr, svd, eig, eigh,
    eigvals, eigvalsh, matrix_rank, matrix_power, multi_dot, cross, corrcoef, cov,
)
from ..ops.math import matmul, t  # noqa: F401
