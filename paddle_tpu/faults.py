"""paddle_tpu.faults — deterministic fault injection for the distributed
runtime (the chaos-testing half of the resilience plane).

Reference parity: the *enforce* layer (`paddle/fluid/platform/enforce.h`)
gives the reference typed, catchable failures; its elastic tier
(`distributed/fleet/elastic/manager.py`) assumes failures can be provoked
and survived. This module is the provoking side: every distributed seam in
the framework (PS RPC, fleet message bus, elastic heartbeat, DataLoader
workers, serving dispatch, checkpoint I/O) carries a *named injection
site*, and a flag-gated registry decides — deterministically — whether a
given site hit turns into a connection reset, a timeout, a delay, or a
torn write. The training guard plane (`paddle_tpu.guard`) adds the loop
seams: `guard.step` (inside the supervised train step — `delay` wedges it
under the watchdog, `error` crashes it), `guard.snapshot` (crash point
between a guard checkpoint's payload and its commit record) and
`guard.snapshot.write` (torn checkpoint payload, via `mangle()`). The
fleet serving tier (`serving/fleet.py`) adds the replica-pool seams:
`router.dispatch` (before each routed send — `conn_reset` drives the
failover drills), `replica.register` (rendezvous with the fleet store)
and `replica.drain` (the graceful-drain path). The PS durability plane
(`distributed/ps/wal.py`) adds the storage seams: `ps.wal.write` (torn
WAL append, via `mangle()` — recovery truncates to the intact prefix
and counts `ps.wal.fallbacks`) and `ps.snapshot.commit` (crash point
between a snapshot's payload write and its manifest commit — recovery
detects the orphaned newer payload and falls back to the previous
generation plus WAL replay).

Spec grammar (`FLAGS_fault_inject`, also `register()`/`inject()`):

    site:kind[:p=PROB][:seed=N][:times=K][:after=N][:delay=SECS]

  - `site`   — the injection-site name; a spec site matches a hit site
               exactly OR as a dotted prefix (`ps.rpc` matches
               `ps.rpc.send` and `ps.rpc.recv`).
  - `kind`   — `conn_reset` (ConnectionResetError), `timeout`
               (TimeoutError), `error` (InjectedFault/RuntimeError),
               `delay` (sleep `delay` seconds then continue), `torn`
               (truncate a payload — fires only via `mangle()`).
  - `p`      — fire probability per eligible hit (default 1.0), drawn
               from a per-spec `random.Random(seed)` so a seeded spec
               produces the SAME hit sequence on every run.
  - `times`  — total fires allowed (0 = unlimited).
  - `after`  — eligible only after this many hits at matching sites.

Multiple specs are separated by `;` (or `,`):
`FLAGS_fault_inject="ps.rpc:conn_reset:p=0.2:seed=7;bus.send:delay=0.05"`.

Hot-path contract (same as `FLAGS_monitor`): instrumented seams guard
with `if _faults._ENABLED: _faults.check("site")` — the disabled path is
one module-attribute load, no lookup, no allocation, and no per-site
bookkeeping. With faults on, every `check()` counts the hit, and every
fire increments `faults.injected` / `faults.injected.<site>` in
`paddle_tpu.monitor` (when the monitor plane is enabled) so chaos runs
are observable next to the recovery counters they provoke
(`ps.retries`, `ps.reconnects`, `bus.reconnects`,
`dataloader.worker_restarts`, `ckpt.fallbacks`).
"""
from __future__ import annotations

import functools
import random
import re
import threading
import time
from typing import Dict, List, Optional

from .core import flags as _flags
from . import monitor as _monitor

__all__ = [
    "InjectedFault", "InjectedConnectionReset", "InjectedTimeout",
    "FaultSpecError",
    "enabled", "check", "site", "mangle",
    "register", "unregister", "inject", "clear", "active", "stats",
    "clear_site",
]


class InjectedFault(RuntimeError):
    """Generic injected failure (kind `error`)."""


class InjectedConnectionReset(ConnectionResetError):
    """Injected transport reset (kind `conn_reset`) — an OSError subclass,
    so retry/reconnect paths treat it exactly like a real peer reset."""


class InjectedTimeout(TimeoutError):
    """Injected deadline expiry (kind `timeout`)."""


class FaultSpecError(ValueError):
    """Malformed `FLAGS_fault_inject` spec string."""


_KINDS = ("conn_reset", "timeout", "error", "delay", "torn")


class _FaultSpec:
    __slots__ = ("site", "kind", "p", "seed", "times", "after", "delay",
                 "_rng", "_hits", "_fired")

    def __init__(self, site: str, kind: str, p: float = 1.0, seed: int = 0,
                 times: int = 0, after: int = 0, delay: float = 0.01):
        if kind not in _KINDS:
            raise FaultSpecError(
                f"fault kind {kind!r} not in {_KINDS} (site {site!r})")
        if not site:
            raise FaultSpecError("fault spec needs a site name")
        self.site, self.kind = site, kind
        self.p, self.seed = float(p), int(seed)
        self.times, self.after = int(times), int(after)
        self.delay = float(delay)
        self._rng = random.Random(self.seed)
        self._hits = 0
        self._fired = 0

    def matches(self, site: str) -> bool:
        return site == self.site or site.startswith(self.site + ".")

    def should_fire(self) -> bool:
        """One eligible hit; caller holds the registry lock."""
        self._hits += 1
        if self._hits <= self.after:
            return False
        if self.times and self._fired >= self.times:
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        self._fired += 1
        return True

    def describe(self) -> str:
        return (f"{self.site}:{self.kind}:p={self.p}:seed={self.seed}"
                f":times={self.times}:after={self.after}"
                + (f":delay={self.delay}" if self.kind == "delay" else ""))


def _parse(spec: str) -> List[_FaultSpec]:
    out = []
    for part in re.split(r"[;,]", spec):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise FaultSpecError(
                f"fault spec {part!r} needs at least site:kind")
        site_name, kind = fields[0].strip(), fields[1].strip()
        kw: Dict[str, float] = {}
        for opt in fields[2:]:
            if "=" not in opt:
                raise FaultSpecError(f"fault option {opt!r} is not k=v "
                                     f"(in {part!r})")
            k, v = opt.split("=", 1)
            k = k.strip()
            if k not in ("p", "seed", "times", "after", "delay"):
                raise FaultSpecError(f"unknown fault option {k!r} "
                                     f"(in {part!r})")
            kw[k] = float(v)
        out.append(_FaultSpec(site_name, kind, **kw))
    return out


# ---- registry ---------------------------------------------------------------
# Flag-sourced specs (replaced wholesale on every FLAGS_fault_inject set)
# and programmatic specs (register()/inject()) are tracked separately so
# the conftest leak guard can restore each origin independently.

_LOCK = threading.Lock()
_FLAG_SPECS: List[_FaultSpec] = []
_PROG_SPECS: List[_FaultSpec] = []
_SITE_HITS: Dict[str, int] = {}
_SITE_INJECTED: Dict[str, int] = {}

_ENABLED: bool = False


def _recompute_enabled() -> None:
    global _ENABLED
    _ENABLED = bool(_FLAG_SPECS or _PROG_SPECS)


def _on_flag(value) -> None:
    specs = _parse(str(value)) if value else []
    with _LOCK:
        _FLAG_SPECS[:] = specs
        _recompute_enabled()


_flags.watch_flag("fault_inject", _on_flag)
if _flags.flag("fault_inject"):  # seeded from the environment at import
    _on_flag(_flags.flag("fault_inject"))


def enabled() -> bool:
    return _ENABLED


def register(spec: str) -> List[_FaultSpec]:
    """Programmatically arm fault spec(s); returns handles for
    `unregister`. Prefer the `inject()` context manager in tests."""
    specs = _parse(spec)
    with _LOCK:
        _PROG_SPECS.extend(specs)
        _recompute_enabled()
    return specs


def unregister(specs: List[_FaultSpec]) -> None:
    with _LOCK:
        for s in specs:
            if s in _PROG_SPECS:
                _PROG_SPECS.remove(s)
        _recompute_enabled()


class _InjectContext:
    """`with faults.inject("ps.rpc:conn_reset:times=1"): ...` — arms the
    spec(s) for the block and disarms them on exit, even on error."""

    def __init__(self, spec: str):
        self._spec = spec
        self._handles: Optional[List[_FaultSpec]] = None

    def __enter__(self):
        self._handles = register(self._spec)
        return self

    def __exit__(self, *exc):
        if self._handles is not None:
            unregister(self._handles)
            self._handles = None
        return False


def inject(spec: str) -> _InjectContext:
    return _InjectContext(spec)


def clear(flag_specs: bool = True, programmatic: bool = True) -> None:
    """Disarm everything (counters included)."""
    with _LOCK:
        if flag_specs:
            _FLAG_SPECS.clear()
        if programmatic:
            _PROG_SPECS.clear()
        _SITE_HITS.clear()
        _SITE_INJECTED.clear()
        _recompute_enabled()


def clear_site(site_name: str) -> None:
    """Disarm every spec matching `site_name` (respawned DataLoader
    workers call this so an inherited fork-copied worker-kill spec cannot
    re-kill the replacement forever)."""
    with _LOCK:
        _FLAG_SPECS[:] = [s for s in _FLAG_SPECS
                          if not s.matches(site_name)]
        _PROG_SPECS[:] = [s for s in _PROG_SPECS
                          if not s.matches(site_name)]
        _recompute_enabled()


def active() -> List[str]:
    with _LOCK:
        return [s.describe() for s in _FLAG_SPECS + _PROG_SPECS]


def stats() -> Dict[str, Dict[str, int]]:
    """Per-site {hits, injected} counts (empty when faults never armed —
    the disabled path records nothing)."""
    with _LOCK:
        sites = set(_SITE_HITS) | set(_SITE_INJECTED)
        return {s: {"hits": _SITE_HITS.get(s, 0),
                    "injected": _SITE_INJECTED.get(s, 0)}
                for s in sorted(sites)}


# ---- the injection points ---------------------------------------------------

def _fire_lookup(site_name: str, torn_only: bool) -> Optional[_FaultSpec]:
    with _LOCK:
        _SITE_HITS[site_name] = _SITE_HITS.get(site_name, 0) + 1
        for spec in _FLAG_SPECS + _PROG_SPECS:
            if (spec.kind == "torn") is not torn_only:
                continue
            if spec.matches(site_name) and spec.should_fire():
                _SITE_INJECTED[site_name] = \
                    _SITE_INJECTED.get(site_name, 0) + 1
                return spec
    return None


def _account(site_name: str) -> None:
    if _monitor._ENABLED:
        _monitor.count("faults.injected")
        _monitor.count(f"faults.injected.{site_name}")


def check(site_name: str) -> None:
    """One hit at a named site. No-op unless an armed spec matches AND
    fires; then raises (conn_reset/timeout/error) or sleeps (delay).
    Callers gate with `if _faults._ENABLED:` so the disabled path never
    reaches here."""
    if not _ENABLED:
        return
    spec = _fire_lookup(site_name, torn_only=False)
    if spec is None:
        return
    _account(site_name)
    if spec.kind == "delay":
        time.sleep(spec.delay)
        return
    if spec.kind == "conn_reset":
        raise InjectedConnectionReset(
            f"fault injected at {site_name}: connection reset")
    if spec.kind == "timeout":
        raise InjectedTimeout(
            f"fault injected at {site_name}: timeout")
    raise InjectedFault(f"fault injected at {site_name}")


def mangle(site_name: str, data: bytes) -> bytes:
    """Payload-corruption hook (kind `torn`): a firing spec truncates the
    bytes to half length — the write path persists the torn payload and
    the READ path must detect it (checksums) and fall back."""
    if not _ENABLED:
        return data
    spec = _fire_lookup(site_name, torn_only=True)
    if spec is None:
        return data
    _account(site_name)
    return data[: len(data) // 2]


class _Site:
    """Context manager + decorator form of `check()`:

        with faults.site("ckpt.write"):
            ...
        @faults.site("ps.rpc")
        def rpc(...): ...
    """

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        check(self.name)
        return self

    def __exit__(self, *exc):
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if _ENABLED:
                check(self.name)
            return fn(*args, **kwargs)
        return wrapper


def site(name: str) -> _Site:
    return _Site(name)
