"""tpu-lint level 4: concurrency analysis (whole-package AST pass).

Levels 1-3 guard the trace -> ProgramDesc -> HLO path; this level guards
the THREADS the robustness planes run on. A pure-AST pass (no imports of
the scanned code) builds the static lock-acquisition graph — which locks
are taken while holding which others, tracked through `with self._lock:`
blocks, bare `.acquire()`/`.release()` calls, and `self.method(...)`
calls ONE level deep — and reports three rules:

  lock-order            two code paths acquire the same pair of locks in
                        opposite orders: the canonical deadlock. The
                        finding names BOTH sites.
  blocking-under-lock   unbounded blocking reachable inside a held-lock
                        region: socket recv/accept, zero-arg
                        `queue.get()` / `.join()` / `.wait()` (no
                        timeout), `time.sleep(>= SLEEP_THRESHOLD_S)`, or
                        an RPC `call_with_retry` — any of these wedges
                        every other thread contending the lock for the
                        full blocking duration.
  unregistered-thread   a raw `threading.Thread(...)` spawn outside the
                        `utils/syncwatch.py` ThreadRegistry — invisible
                        to the leak fixtures and the
                        `monitor threads` live table.

Lock identity is name-based: `self._lock` in class C is `C._lock`,
module-level `_LOCK` keeps its name, `self._locks[i]` collapses to
`C._locks[]` (a same-name CLASS — ordered same-class acquisition, like
the PS client's ascending shard order, is the caller's protocol and
never forms an edge). A `with`/`acquire()` target counts as a lock when
it was assigned from `threading.Lock/RLock` / `syncwatch.lock/rlock` in
the same module, or when its terminal name looks like one
(`*lock*`/`*mutex*`/`_mu`).

Suppressions are the standard `# tpu-lint: disable=rule` comments; a
`lock-order` finding is dropped when EITHER of its two sites is
suppressed. The runtime half of this plane is `utils/syncwatch.py`,
which observes the same graph live under FLAGS_sync_watch.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from .base import Finding, Suppressions

__all__ = ["SLEEP_THRESHOLD_S", "analyze_source", "analyze_paths",
           "lock_graph", "find_cycles"]

# a `time.sleep(c)` with constant c at/above this, under a held lock,
# is a real stall for every contending thread
SLEEP_THRESHOLD_S = 0.05

_LOCK_NAME_RE = re.compile(r"(^|_)(lock|locks|mu|mutex)\d*$", re.I)
# `.get()` is only a QUEUE get when the receiver is queue-shaped —
# Counter.get()/dict.get(k) must not fire
_QUEUE_NAME_RE = re.compile(
    r"(^|_)(q|queue|queues|jobs|tasks|inbox|mailbox|work)\d*$", re.I)
_BLOCKING_SOCKET = ("recv", "recv_into", "recvfrom", "accept")


def _dotted(node) -> Tuple[str, ...]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def _looks_like_lock(name: str) -> bool:
    return bool(_LOCK_NAME_RE.search(name))


# ---------------------------------------------------------------------------
# per-module scan
# ---------------------------------------------------------------------------

class _Module:
    """One parsed file: class->method map, known lock attrs, imports."""

    def __init__(self, tree: ast.Module, path: str):
        self.path = path
        self.classes: Dict[str, Dict[str, ast.FunctionDef]] = {}
        self.known_locks: set = set()
        self.thread_from_threading = False   # `from threading import Thread`
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "threading":
                if any(a.name == "Thread" for a in node.names):
                    self.thread_from_threading = True
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = {
                    n.name: n for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
        # known lock assignments: `x = threading.Lock()` /
        # `self._lock = syncwatch.lock(...)` anywhere in the module
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            ctor = _dotted(node.value.func)
            if not ctor:
                continue
            is_lock = ctor[-1] in ("Lock", "RLock") or \
                (len(ctor) >= 2 and ctor[-2] in ("syncwatch", "_syncwatch")
                 and ctor[-1] in ("lock", "rlock"))
            if not is_lock:
                continue
            for tgt in node.targets:
                parts = _dotted(tgt)
                if parts:
                    self.known_locks.add(parts[-1])

    def lock_id(self, node, cls: Optional[str]) -> Optional[str]:
        """Resolve a with-item / acquire() target to a lock name, or
        None when it does not look like a lock."""
        suffix = ""
        if isinstance(node, ast.Subscript):
            node, suffix = node.value, "[]"
        parts = _dotted(node)
        if not parts:
            return None
        name = parts[-1]
        if name not in self.known_locks and not _looks_like_lock(name):
            return None
        if parts[0] == "self" and cls:
            parts = (cls,) + parts[1:]
        return ".".join(parts) + suffix


class _Edges:
    """The static lock graph: (src, dst) -> first site, where src->dst
    means "dst acquired while src held"."""

    def __init__(self):
        self.sites: Dict[Tuple[str, str],
                         Tuple[str, int, str]] = {}

    def add(self, src: str, dst: str, path: str, line: int,
            func: str) -> None:
        if src != dst:
            self.sites.setdefault((src, dst), (path, line, func))


class _FuncScan:
    """Walk one function's statements in order, tracking the held-lock
    stack structurally through `with` blocks and linearly through
    `.acquire()`/`.release()`; recurse one level into `self.method()`
    calls made while holding a lock."""

    def __init__(self, mod: _Module, cls: Optional[str],
                 fn, findings: List[Finding], edges: _Edges,
                 depth: int = 0, held: Optional[List[str]] = None,
                 via: str = ""):
        self.mod, self.cls, self.fn = mod, cls, fn
        self.findings, self.edges = findings, edges
        self.depth = depth
        self.held: List[str] = list(held or [])
        self.qual = (f"{cls}.{fn.name}" if cls else fn.name) + via

    def _add(self, rule: str, node, message: str) -> None:
        self.findings.append(Finding(
            rule, message, path=self.mod.path, line=node.lineno,
            col=node.col_offset, func=self.qual))

    def run(self) -> None:
        self._block(self.fn.body)

    # -- statement walking --
    def _block(self, stmts) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                self._expr(item.context_expr)
                lid = self.mod.lock_id(item.context_expr, self.cls)
                if lid is not None:
                    self._acquire(lid, item.context_expr)
                    acquired.append(lid)
            self._block(stmt.body)
            for lid in reversed(acquired):
                self._release(lid)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return          # nested defs run later, not in this region
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While,
                             ast.Try)):
            for expr in ast.iter_child_nodes(stmt):
                if isinstance(expr, ast.expr):
                    self._expr(expr)
            before = list(self.held)
            for attr in ("body", "orelse", "finalbody"):
                self.held = list(before)
                self._block(getattr(stmt, attr, []) or [])
            for h in getattr(stmt, "handlers", []) or []:
                self.held = list(before)
                self._block(h.body)
            self.held = before
            return
        # linear statement: scan every call; toggle bare acquire/release
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._call(node)

    # -- lock bookkeeping --
    def _acquire(self, lid: str, node) -> None:
        for h in self.held:
            self.edges.add(h, lid, self.mod.path, node.lineno, self.qual)
        self.held.append(lid)

    def _release(self, lid: str) -> None:
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i] == lid:
                del self.held[i]
                return

    # -- calls --
    def _expr(self, expr) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._call(node)

    def _call(self, call: ast.Call) -> None:
        f = call.func
        parts = _dotted(f)
        # unregistered-thread fires held or not
        if parts and parts[-1] == "Thread":
            registered = len(parts) >= 2 and \
                parts[-2] in ("syncwatch", "_syncwatch")
            raw = (len(parts) >= 2 and parts[-2] == "threading") or \
                (len(parts) == 1 and self.mod.thread_from_threading)
            if raw and not registered:
                self._add("unregistered-thread", call,
                          "raw threading.Thread() outside the "
                          "ThreadRegistry — spawn via syncwatch.Thread("
                          "..., owner=__name__) so leak fixtures and "
                          "`monitor threads` can see it")
        if isinstance(f, ast.Attribute):
            # bare acquire()/release() on a lock-looking target
            lid = self.mod.lock_id(f.value, self.cls)
            if lid is not None and f.attr == "acquire":
                self._acquire(lid, call)
            elif lid is not None and f.attr == "release":
                self._release(lid)
        if self.held:
            reason = self._blocking_reason(call, parts)
            if reason is not None:
                self._add("blocking-under-lock", call,
                          f"{reason} while holding "
                          f"{', '.join(repr(h) for h in self.held)} — "
                          "every contending thread stalls for the full "
                          "blocking duration; move it outside the "
                          "critical section or bound it with a timeout")
            # one level deep: self.method() called under a held lock
            if self.depth == 0 and isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == "self" and self.cls:
                m = self.mod.classes.get(self.cls, {}).get(f.attr)
                if m is not None and m is not self.fn:
                    _FuncScan(self.mod, self.cls, m, self.findings,
                              self.edges, depth=1, held=self.held,
                              via=f" (called holding "
                                  f"{', '.join(self.held)})").run()

    def _blocking_reason(self, call: ast.Call,
                         parts: Tuple[str, ...]) -> Optional[str]:
        if not parts:
            return None
        name = parts[-1]
        if isinstance(call.func, ast.Attribute):
            if name in _BLOCKING_SOCKET:
                return f"socket .{name}()"
            has_kw = {kw.arg for kw in call.keywords}
            if name == "get" and not call.args and not call.keywords \
                    and len(parts) >= 2 \
                    and _QUEUE_NAME_RE.search(parts[-2]):
                return "queue .get() with no timeout"
            if name in ("join", "wait") and not call.args and \
                    "timeout" not in has_kw:
                return f".{name}() with no timeout"
        if name == "call_with_retry":
            return "RPC call_with_retry()"
        if name == "sleep" and (len(parts) == 1 or parts[-2] == "time"):
            if call.args and isinstance(call.args[0], ast.Constant):
                try:
                    secs = float(call.args[0].value)
                except (TypeError, ValueError):
                    return None
                if secs >= SLEEP_THRESHOLD_S:
                    return f"time.sleep({secs:g})"
        return None


def _scan_module(src: str, path: str
                 ) -> Tuple[List[Finding], _Edges, Suppressions]:
    tree = ast.parse(src, filename=path)
    mod = _Module(tree, path)
    findings: List[Finding] = []
    edges = _Edges()
    for cls, methods in mod.classes.items():
        for m in methods.values():
            _FuncScan(mod, cls, m, findings, edges).run()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FuncScan(mod, None, node, findings, edges).run()
    # module-level statements (thread spawns in script blocks)
    top = _FuncScan(mod, None,
                    ast.FunctionDef(name="<module>", args=None,
                                    body=[], decorator_list=[]),
                    findings, edges)
    for stmt in tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            top._stmt(stmt)
    return findings, edges, Suppressions(src)


# ---------------------------------------------------------------------------
# whole-run aggregation: inversions + cycles over the merged graph
# ---------------------------------------------------------------------------

def find_cycles(sites: Dict[Tuple[str, str], Tuple[str, int, str]]
                ) -> List[List[str]]:
    """Cycles in the merged lock graph (node path, last edge closes the
    loop), deduplicated by node set. Pairwise inversions come out as
    2-cycles."""
    adj: Dict[str, List[str]] = {}
    for (a, b) in sites:
        adj.setdefault(a, []).append(b)
    cycles, seen = [], set()

    def dfs(node, path, on_path):
        for nxt in adj.get(node, ()):
            if nxt in on_path:
                cyc = path[path.index(nxt):]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    cycles.append(cyc + [nxt])
            elif (node, nxt) not in visited_edges:
                visited_edges.add((node, nxt))
                on_path.add(nxt)
                dfs(nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    visited_edges: set = set()
    for start in sorted(adj):
        dfs(start, [start], {start})
    return cycles


def _order_findings(sites: Dict[Tuple[str, str], Tuple[str, int, str]],
                    supp: Dict[str, Suppressions]) -> List[Finding]:
    out = []
    for cyc in find_cycles(sites):
        edge_sites = []
        suppressed = False
        for a, b in zip(cyc, cyc[1:]):
            path, line, func = sites[(a, b)]
            edge_sites.append((a, b, path, line, func))
            s = supp.get(path)
            if s is not None and s.suppressed("lock-order", line):
                suppressed = True
        if suppressed:
            continue
        a, b, path, line, func = edge_sites[-1]
        others = "; ".join(
            f"'{x}' -> '{y}' at {p}:{ln} (in {fn})"
            for x, y, p, ln, fn in edge_sites[:-1])
        out.append(Finding(
            "lock-order",
            f"inconsistent lock order: acquiring '{b}' while holding "
            f"'{a}' closes the cycle {' -> '.join(cyc)} — established "
            f"by {others}; two threads running these paths "
            "concurrently deadlock", path=path, line=line, func=func))
    return out


def analyze_source(src: str, path: str = "<src>") -> List[Finding]:
    """Single-file entry (tests, apply_pass): blocking/thread findings
    plus any intra-file lock-order inversions, suppression-applied."""
    findings, edges, supp = _scan_module(src, path)
    findings = supp.apply(findings)
    findings += _order_findings(edges.sites, {path: supp})
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_paths(paths: List[str]
                  ) -> Tuple[List[Finding], int,
                             Dict[Tuple[str, str], Tuple[str, int, str]]]:
    """Whole-package entry: per-file findings plus lock-order findings
    over the MERGED cross-file graph. Returns (findings, n_files,
    edge-site map) — the site map is the checked-in-gate's proof that
    the repo's own lock graph is cycle-free."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                files.extend(os.path.join(root, n)
                             for n in sorted(names) if n.endswith(".py"))
        elif os.path.isfile(p):
            files.append(p)
        else:
            raise FileNotFoundError(p)
    findings: List[Finding] = []
    merged: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    supp: Dict[str, Suppressions] = {}
    for path in files:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            fs, edges, s = _scan_module(src, path)
        except SyntaxError:
            continue
        findings.extend(s.apply(fs))
        supp[path] = s
        for k, v in edges.sites.items():
            merged.setdefault(k, v)
    findings += _order_findings(merged, supp)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, len(files), merged


def lock_graph(paths: List[str]
               ) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
    """The merged static lock graph of `paths` (edge -> first site)."""
    return analyze_paths(paths)[2]
