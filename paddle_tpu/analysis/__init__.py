"""paddle_tpu.analysis — tpu-lint, the static-analysis plane.

Four levels (trace safety, graph hygiene, collective-deadlock detection,
thread/lock concurrency), all runnable offline and at compile time:

  1. source lint (`analysis.lint`): AST scan of trace-destined functions
     for host syncs, tensor-dependent Python control flow, traced print,
     stdlib RNG, and shape-capture retrace forks;
  2. graph analysis (`analysis.graph`): jaxpr/Program walks for dead ops,
     unused inputs, implicit f64 widenings, host callbacks, and
     collective-ordering verification across ranks/pipeline stages;
  3. concurrency analysis (`analysis.concurrency`): whole-package AST
     pass building the static lock-acquisition graph — `lock-order`
     inversions, `blocking-under-lock`, `unregistered-thread` (the
     static half of `utils/syncwatch.py`, which observes the same graph
     live under `FLAGS_sync_watch`);
  4. driver: `python -m paddle_tpu.analysis <paths>` (severities,
     `# tpu-lint: disable=RULE` suppressions, `--json`), the same rules as
     registered passes (`prog.apply_pass('lint')`, `'concurrency'`,
     `'dead_op_elim'` in `static/passes.py`), and a trace-time hook behind
     `FLAGS_lint` (warnings + `lint.findings`/`lint.files` monitor
     counters; the disabled path is one module-attribute check, like
     `faults`/`monitor`).
"""
from __future__ import annotations

from typing import List

from ..core import flags as _flags
from .base import Finding, RULES, Severity  # noqa: F401
from .lint import (  # noqa: F401
    lint_callable, lint_file, lint_paths, lint_source)

__all__ = [
    "Finding", "RULES", "Severity",
    "lint_source", "lint_file", "lint_paths", "lint_callable",
    "analyze_jaxpr", "analyze_program",
    "collective_sequence", "verify_collective_order",
    "verify_stage_chain", "verify_stage_assignment",
    "analyze_concurrency", "analyze_concurrency_paths", "lock_graph",
    "enabled", "enable", "disable", "lint_traced", "main",
]

# Hot-path gate (faults/monitor pattern): the jit trace hooks read this
# module attribute; `watch_flag` keeps it in sync with set_flags.
_ENABLED: bool = bool(_flags.flag("lint"))


def _on_flag(value) -> None:
    global _ENABLED
    _ENABLED = bool(value)


_flags.watch_flag("lint", _on_flag)


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    _flags.set_flags({"lint": True})


def disable() -> None:
    _flags.set_flags({"lint": False})


# jax-dependent level 2 lives in .graph; re-export lazily so importing the
# linter (pure stdlib ast) never pulls the tracer machinery
def __getattr__(name):
    if name in ("analyze_jaxpr", "analyze_program", "collective_sequence",
                "verify_collective_order", "verify_stage_chain",
                "verify_stage_assignment", "CollectiveDesc",
                "iter_eqns", "live_eqn_mask"):
        from . import graph as _graph
        return getattr(_graph, name)
    # level 4 (concurrency) stays lazy like level 2: importing the core
    # linter must not grow
    if name in ("analyze_concurrency", "analyze_concurrency_paths",
                "lock_graph", "find_cycles"):
        from . import concurrency as _concurrency
        return {"analyze_concurrency": _concurrency.analyze_source,
                "analyze_concurrency_paths": _concurrency.analyze_paths,
                "lock_graph": _concurrency.lock_graph,
                "find_cycles": _concurrency.find_cycles}[name]
    if name == "main":
        from .cli import main as _main
        return _main
    raise AttributeError(name)


# ---- trace-time hook (FLAGS_lint) ------------------------------------------

# functions already linted this process (code object identity): tracing the
# same capture for a new shape signature must not re-lint or re-warn
_LINTED_KEYS = set()
_LINTED_FILES = set()


def lint_traced(fn, where: str = "trace") -> List[Finding]:
    """Lint `fn` as a traced region, once per function per process. Called
    from `jit/to_static.py` / `jit/train_step.py` / `parallel/spmd.py` at
    trace time when `FLAGS_lint` is on. Emits a warning per finding and
    bumps the `lint.findings` / `lint.files` monitor counters."""
    import warnings

    target = getattr(fn, "__func__", fn)
    code = getattr(target, "__code__", None)
    key = code if code is not None else id(target)
    if key in _LINTED_KEYS:
        return []
    _LINTED_KEYS.add(key)
    findings = lint_callable(fn)
    from .. import monitor as _monitor
    src_file = getattr(code, "co_filename", None)
    if src_file is not None and src_file not in _LINTED_FILES:
        _LINTED_FILES.add(src_file)
        _monitor.count("lint.files")
    if findings:
        _monitor.count("lint.findings", len(findings))
        for f in findings:
            warnings.warn(f"tpu-lint[{where}]: {f.format()}", stacklevel=3)
    return findings


def _reset_trace_cache() -> None:
    """Test hook: forget which functions were already linted."""
    _LINTED_KEYS.clear()
    _LINTED_FILES.clear()
