"""tpu-lint level 1: source lint for trace-destined Python functions.

Reuses the same AST machinery `jit/dy2static.py` parses functions with, but
for ANALYSIS instead of rewriting: scan functions destined for `@to_static`
/ `TrainStep` for the hazards that only surface at runtime as a
ConcretizationError, a silent retrace storm, or a host-pinned step.

Two scan modes per function:
  - trace-destined (forward methods, @to_static/@declarative/@jax.jit
    decorated, or names passed as entry points): full rule set, with a
    light intra-function taint analysis seeding every non-self parameter
    (minus ones with scalar/str/None defaults) as a tensor;
  - --all mode (every other def): syntactic rules only (.numpy()-family
    host syncs, stdlib RNG, print) — the taint assumption "parameters are
    tensors" is only sound for trace-destined code.

Suppression: `# tpu-lint: disable=rule-a,rule-b` on the offending line, or
on a comment-only line for file-wide scope (see base.Suppressions).
"""
from __future__ import annotations

import ast
import inspect
import re
import textwrap
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from .base import Finding, Suppressions

__all__ = ["lint_source", "lint_file", "lint_callable", "lint_paths"]

# method calls that force a device->host sync on a tensor receiver
_HOST_SYNC_METHODS = {"numpy", "item", "tolist"}
# builtins that concretize a tensor argument
_HOST_SYNC_BUILTINS = {"float", "int", "bool"}
# attribute reads that are STATIC metadata under trace (not data)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "stop_gradient"}
# builtins whose result is host-static regardless of tensor args (type
# tests and reflection — never data-dependent)
_STATIC_BUILTINS = {"isinstance", "issubclass", "hasattr", "callable",
                    "type", "id", "repr"}
# decorator name suffixes that mark a function trace-destined
_TRACED_DECORATORS = {"to_static", "declarative", "jit"}
# fused-update advisory: eager step/update functions looping per-parameter
_UPDATE_FUNC_RE = re.compile(r"step|update", re.IGNORECASE)
_PARAMISH_RE = re.compile(r"param|grad|slot|moment|velocit", re.IGNORECASE)
# call roots/attrs in a loop body that indicate per-iteration device work
_ARRAY_CALL_ROOTS = {"jnp", "jax", "lax", "paddle", "run_op"}
# default values that mark a parameter as non-tensor config
_SCALAR_DEFAULT_TYPES = (bool, int, float, str, bytes, type(None))
# raw socket operations that belong in the substrate (utils/net.py);
# the substrate itself and the C-API mirror (csrc/predict_capi.cpp
# callers) are exempt by path
_RAW_SOCKET_CALLS = {"recv", "sendall", "create_connection"}
_RAW_SOCKET_EXEMPT_RE = re.compile(
    r"(^|[/\\])(utils[/\\]net\.py$|csrc[/\\])")


def _dotted(node) -> Tuple[str, ...]:
    """('np', 'random', 'rand') for np.random.rand; () when not a pure
    dotted name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def _is_stdlib_random(chain: Tuple[str, ...]) -> bool:
    if not chain:
        return False
    if chain[0] == "random" and len(chain) > 1:
        return True
    return len(chain) > 2 and chain[0] in ("np", "numpy") \
        and chain[1] == "random"


def _decorator_traced(dec) -> bool:
    target = dec.func if isinstance(dec, ast.Call) else dec
    chain = _dotted(target)
    return bool(chain) and chain[-1] in _TRACED_DECORATORS


class _Taint:
    """Expression classification: (tensor-tainted, shape-derived)."""

    def __init__(self, tainted: Set[str]):
        self.tainted = tainted

    def of(self, node) -> Tuple[bool, bool]:
        t = self._of
        if isinstance(node, ast.Name):
            return node.id in self.tainted, False
        if isinstance(node, ast.Attribute):
            bt, bs = t(node.value)
            if node.attr in _STATIC_ATTRS:
                # x.shape/x.ndim of a tensor: static metadata, but flag
                # branches on it (shape-capture) — each shape forks a trace
                return False, (bt or bs) and node.attr in ("shape", "ndim")
            return bt, bs
        if isinstance(node, ast.Subscript):
            bt, bs = t(node.value)
            it, is_ = t(node.slice)
            return bt or it, bs or is_
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            args_tb = [t(a) for a in node.args] + \
                [t(k.value) for k in node.keywords]
            any_t = any(a for a, _ in args_tb)
            any_s = any(s for _, s in args_tb)
            if len(chain) == 1 and chain[0] in _STATIC_BUILTINS:
                return False, False
            if chain == ("len",):
                at, _ = t(node.args[0]) if node.args else (False, False)
                return False, at          # len(tensor) is static metadata
            if chain and chain[-1] in _HOST_SYNC_METHODS:
                return False, False       # result is a host value
            if len(chain) == 1 and chain[0] in _HOST_SYNC_BUILTINS:
                return False, any_s       # int(x.shape[0]) stays shapey
            ft, fs = t(node.func)
            return ft or any_t, fs or any_s
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False, False       # identity tests are host-static
            parts = [t(node.left)] + [t(c) for c in node.comparators]
            any_t = any(a for a, _ in parts)
            return any_t, (not any_t) and any(s for _, s in parts)
        if isinstance(node, (ast.BoolOp,)):
            parts = [t(v) for v in node.values]
            return any(a for a, _ in parts), any(s for _, s in parts)
        if isinstance(node, ast.BinOp):
            lt, ls = t(node.left)
            rt, rs = t(node.right)
            return lt or rt, ls or rs
        if isinstance(node, ast.UnaryOp):
            return t(node.operand)
        if isinstance(node, ast.IfExp):
            parts = [t(node.test), t(node.body), t(node.orelse)]
            return any(a for a, _ in parts), any(s for _, s in parts)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            parts = [t(e) for e in node.elts]
            return any(a for a, _ in parts), any(s for _, s in parts)
        if isinstance(node, ast.Starred):
            return t(node.value)
        if isinstance(node, ast.NamedExpr):
            return t(node.value)
        return False, False

    _of = of


def _seed_params(fdef) -> Set[str]:
    """Non-self parameters assumed to carry tensors — minus ones whose
    DEFAULT is a plain scalar/str/None (config knobs, not data)."""
    a = fdef.args
    params = [p.arg for p in a.posonlyargs + a.args]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    scalarish: Set[str] = set()
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        if isinstance(d, ast.Constant) and \
                isinstance(d.value, _SCALAR_DEFAULT_TYPES):
            scalarish.add(p.arg)
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if isinstance(d, ast.Constant) and \
                isinstance(d.value, _SCALAR_DEFAULT_TYPES):
            scalarish.add(p.arg)
        else:
            params.append(p.arg)
    return {p for p in params if p not in scalarish}


def _taint_fixpoint(fdef, seeds: Set[str]) -> Set[str]:
    """Order-insensitive name-taint closure over the function body: a name
    assigned from a tainted expression becomes tainted. Sound
    over-approximation (a name reused for host values stays flagged —
    suppressions cover the rare intentional case)."""
    tainted = set(seeds)
    assigns = []
    for n in ast.walk(fdef):
        if isinstance(n, ast.Assign):
            assigns.append((n.targets, n.value))
        elif isinstance(n, ast.AugAssign):
            assigns.append(([n.target], n.value))
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            assigns.append(([n.target], n.value))
        elif isinstance(n, ast.NamedExpr):
            assigns.append(([n.target], n.value))
        elif isinstance(n, ast.For):
            assigns.append(([n.target], n.iter))
    for _ in range(len(assigns) + 1):
        changed = False
        tt = _Taint(tainted)
        for targets, value in assigns:
            vt, _ = tt.of(value)
            if not vt:
                continue
            for tgt in targets:
                for nm in ast.walk(tgt):
                    if isinstance(nm, ast.Name) and nm.id not in tainted:
                        tainted.add(nm.id)
                        changed = True
        if not changed:
            break
    return tainted


class _RegionLinter(ast.NodeVisitor):
    """Flagging pass over one traced function's body (nested defs and
    lambdas included — the traced region covers them)."""

    def __init__(self, path: str, func: str, tainted: Set[str],
                 full: bool, raw_socket_exempt: bool = False):
        self.path, self.func = path, func
        self.taint = _Taint(tainted)
        self.full = full            # taint-based rules enabled
        self.raw_socket_exempt = raw_socket_exempt
        self.findings: List[Finding] = []
        self._loop_depth = 0        # For/While bodies (lazy-sync advisory)
        # names carrying per-iteration values (loop targets + names
        # assigned from them / from array-call results inside the body) —
        # the buffer-retain advisory's lightweight --all-mode taint
        self._loop_names: Set[str] = set()

    def _add(self, rule: str, node, message: str):
        self.findings.append(Finding(
            rule, message, path=self.path, line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0), func=self.func))

    def _add_sync(self, node, message: str):
        """host-sync finding + lazy-sync advisory when it sits in a loop
        body: under FLAGS_lazy_eager (ops/lazy.py) each such call flushes
        the pending segment, so a per-iteration sync re-serializes the
        dispatch stream the lazy executor was batching."""
        self._add("host-sync", node, message)
        if self._loop_depth:
            self._add("lazy-sync", node,
                      "sync point inside a loop body flushes the lazy "
                      "segment every iteration (FLAGS_lazy_eager) — hoist "
                      "it out of the hot loop")

    # -- calls: host syncs, RNG, print --
    def visit_Call(self, node):
        chain = _dotted(node.func)
        if chain and chain[-1] in _HOST_SYNC_METHODS \
                and isinstance(node.func, ast.Attribute):
            self._add_sync(node,
                           f".{chain[-1]}() forces a device->host sync in "
                           "a traced region")
        elif _is_stdlib_random(chain):
            self._add("stdlib-random", node,
                      f"{'.'.join(chain)}() is host RNG: its value is "
                      "burned in at trace time (use paddle ops riding the "
                      "trace key)")
        elif chain == ("print",):
            self._add("traced-print", node,
                      "print() in a traced region runs at trace time only")
        elif self.full and len(chain) == 1 \
                and chain[0] in _HOST_SYNC_BUILTINS and node.args:
            at, _ = self.taint.of(node.args[0])
            if at:
                self._add_sync(node,
                               f"{chain[0]}(tensor) concretizes a traced "
                               "value (device->host sync)")
        elif self.full and len(chain) == 2 and chain[0] in ("np", "numpy") \
                and chain[1] in ("asarray", "array") and node.args:
            at, _ = self.taint.of(node.args[0])
            if at:
                self._add_sync(node,
                               f"{'.'.join(chain)}(tensor) pulls a traced "
                               "value to the host")
        if len(chain) > 1 and chain[-1] in _RAW_SOCKET_CALLS \
                and not self.raw_socket_exempt:
            self._add("raw-socket", node,
                      f".{chain[-1]}() is raw socket I/O outside "
                      "utils/net.py — it bypasses the unified RPC "
                      "substrate (deadlines, retries, auth/TLS, fault "
                      "sites); route through RpcChannel/RpcServer or the "
                      "net.py helpers")
        self.generic_visit(node)

    # -- control flow on tensors / shapes --
    def _check_test(self, node, test, kind: str):
        if not self.full:
            return
        tt, ts = self.taint.of(test)
        if tt:
            self._add("tensor-branch", node,
                      f"`{kind}` on a tensor value is data-dependent "
                      "Python control flow (untraceable predicate)")
        elif ts:
            self._add("shape-capture", node,
                      f"`{kind}` on a tensor shape forks a separate "
                      "compilation per input shape (retrace storm)")

    # -- per-param dispatch loops (fused-update advisory) --
    def visit_For(self, node):
        # Traced regions (full=True) unroll loops into ONE executable, so
        # the per-param-dispatch hazard only exists in eager step/update
        # functions scanned under --all.
        if not self.full and _UPDATE_FUNC_RE.search(self.func) \
                and self._iterates_params(node.iter) \
                and self._loop_dispatches(node):
            self._add("fused-update", node,
                      "per-parameter Python loop doing array math in an "
                      "eager step/update function — each iteration "
                      "dispatches its own executable; fuse into one jitted "
                      "tree-level update (donated, single dispatch)")
        # the iterable is evaluated once, at loop entry — only the body
        # (and else-clause) re-runs per iteration
        self.visit(node.target)
        self.visit(node.iter)
        added = {n.id for n in ast.walk(node.target)
                 if isinstance(n, ast.Name)} - self._loop_names
        self._loop_names |= added
        self._loop_depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self._loop_depth -= 1
        self._loop_names -= added

    @staticmethod
    def _iterates_params(iter_node) -> bool:
        names = [n.id for n in ast.walk(iter_node)
                 if isinstance(n, ast.Name)]
        names += [n.attr for n in ast.walk(iter_node)
                  if isinstance(n, ast.Attribute)]
        return any(_PARAMISH_RE.search(s) for s in names)

    @staticmethod
    def _loop_dispatches(node) -> bool:
        targets = {n.id for n in ast.walk(node.target)
                   if isinstance(n, ast.Name)}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                chain = _dotted(sub.func)
                if chain and (chain[0] in _ARRAY_CALL_ROOTS
                              or chain[-1].lstrip("_").startswith("apply")):
                    return True
            elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                val = sub.value
                if isinstance(val, ast.BinOp) and any(
                        isinstance(n, ast.Name) and n.id in targets
                        for n in ast.walk(val)):
                    return True
        return False

    def visit_If(self, node):
        self._check_test(node, node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_test(node, node.test, "while")
        # the test re-evaluates every iteration: count it as loop body
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_Assert(self, node):
        self._check_test(node, node.test, "assert")
        self.generic_visit(node)

    # -- buffer-retain advisory (self-attribute writes in loop bodies) --
    @staticmethod
    def _is_host_copy(value) -> bool:
        """float(x) / x.item() / np.asarray(x)-style conversions — the
        recommended buffer-retain FIX — produce host values, not buffers."""
        if not isinstance(value, ast.Call):
            return False
        chain = _dotted(value.func)
        if len(chain) == 1 and chain[0] in _HOST_SYNC_BUILTINS:
            return True
        if chain and chain[-1] in _HOST_SYNC_METHODS:
            return True
        return len(chain) == 2 and chain[0] in ("np", "numpy") \
            and chain[1] in ("asarray", "array")

    def _value_steplike(self, value) -> bool:
        """--all-mode stand-in for taint: does the expression touch a
        per-iteration value (a tracked loop name) or produce device work
        (a call rooted in jnp/jax/lax/paddle/run_op)?"""
        if self._is_host_copy(value):
            return False
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                chain = _dotted(sub.func)
                if chain and chain[0] in _ARRAY_CALL_ROOTS:
                    return True
            elif isinstance(sub, ast.Name) and sub.id in self._loop_names:
                return True
        return False

    def _maybe_buffer_retain(self, node, targets, value):
        if not self._loop_depth:
            return
        if self.full:
            steplike, _ = self.taint.of(value)
        else:
            steplike = self._value_steplike(value)
        if not steplike:
            return
        if not self.full:
            # propagate through plain-name rebinds so `loss = step(b);
            # self.last = loss` is caught, not just the direct form
            for t in targets:
                if isinstance(t, ast.Name):
                    self._loop_names.add(t.id)
        for t in targets:
            if not isinstance(t, ast.Attribute):
                continue
            root = t
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in ("self", "cls"):
                self._add("buffer-retain", node,
                          f"`{ast.unparse(t) if hasattr(ast, 'unparse') else t.attr}` "
                          "assigned from a per-step tensor inside a loop — "
                          "the held reference outlives the iteration, "
                          "defeating buffer donation and pinning device "
                          "memory (keep float(...)/np.asarray copies "
                          "instead)")

    def visit_Assign(self, node):
        self._maybe_buffer_retain(node, node.targets, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._maybe_buffer_retain(node, [node.target], node.value)
        self.generic_visit(node)


def _iter_scan_units(tree) -> Iterable[Tuple[ast.AST, bool]]:
    """(function node, is_method) for every top-level and class-level def.
    Nested defs are scanned as part of their parent's region."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, False
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub, True


def _is_trace_destined(fdef, is_method: bool, entries) -> bool:
    if fdef.name in entries or fdef.name == "forward":
        return True
    return any(_decorator_traced(d) for d in fdef.decorator_list)


def lint_source(source: str, path: str = "<string>",
                all_functions: bool = False,
                entries: Sequence[str] = (),
                assume_traced: bool = False) -> List[Finding]:
    """Lint one module's source. Returns suppression-filtered findings."""
    try:
        tree = ast.parse(textwrap.dedent(source))
    except SyntaxError as e:
        return [Finding("parse-error", f"unparseable source: {e}", path=path,
                        line=getattr(e, "lineno", 0) or 0,
                        severity="info")]
    sup = Suppressions(source)
    findings: List[Finding] = []
    entries = set(entries)
    for fdef, is_method in _iter_scan_units(tree):
        traced = assume_traced or _is_trace_destined(fdef, is_method, entries)
        if not traced and not all_functions:
            continue
        if not traced and (fdef.name.startswith("__")
                           and fdef.name.endswith("__")):
            continue                     # dunders are never traced regions
        tainted = _taint_fixpoint(fdef, _seed_params(fdef)) if traced \
            else set()
        linter = _RegionLinter(
            path, fdef.name, tainted, full=traced,
            raw_socket_exempt=bool(_RAW_SOCKET_EXEMPT_RE.search(path)))
        for stmt in fdef.body:
            linter.visit(stmt)
        findings.extend(linter.findings)
    return sup.apply(findings)


def lint_file(path: str, all_functions: bool = False,
              entries: Sequence[str] = ()) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, path=path, all_functions=all_functions,
                       entries=entries)


def lint_paths(paths: Sequence[str], all_functions: bool = False,
               entries: Sequence[str] = ()) -> Tuple[List[Finding], int]:
    """Lint files/directories recursively. Returns (findings, n_files).
    Raises FileNotFoundError for a missing path (CLI maps it to exit 2)."""
    import os
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        elif os.path.isfile(p):
            files.append(p)
        else:
            raise FileNotFoundError(p)
    findings: List[Finding] = []
    for f in files:
        findings.extend(lint_file(f, all_functions=all_functions,
                                  entries=entries))
    return findings, len(files)


def lint_callable(fn, path: Optional[str] = None) -> List[Finding]:
    """Lint a live function/method as a traced region (the trace-time
    FLAGS_lint hook). Source unavailable -> no findings, never an error."""
    fn = inspect.unwrap(getattr(fn, "__dy2static_original__", fn))
    if inspect.ismethod(fn):
        fn = fn.__func__
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        fname = path or inspect.getsourcefile(fn) or "<callable>"
    except (OSError, TypeError):
        return []
    findings = lint_source(src, path=fname, assume_traced=True)
    # re-anchor fixture/<string> line numbers onto the real file
    try:
        base = fn.__code__.co_firstlineno - 1
        for f in findings:
            f.line += base
    except AttributeError:
        pass
    return findings
