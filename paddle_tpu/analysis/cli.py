"""tpu-lint driver: `python -m paddle_tpu.analysis <paths>`.

Text output is one `path:line:col: severity: message [rule]` line per
finding plus a summary; `--json` emits a machine-readable document for CI.
Exit codes: 0 = clean (below --fail-on), 1 = findings at/above --fail-on,
2 = usage error (missing path).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .base import RULES, Finding, severity_at_least
from .lint import lint_paths

__all__ = ["main"]


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="tpu-lint: static trace-safety analysis for paddle_tpu "
                    "code (host syncs, tensor branches, stdlib RNG, retrace "
                    "forks) — see README 'Static analysis'")
    p.add_argument("paths", nargs="+",
                   help="files or directories to lint (dirs recurse)")
    p.add_argument("--all", action="store_true", dest="all_functions",
                   help="scan every function with the syntactic rules, not "
                        "just trace-destined ones (forward/@to_static)")
    p.add_argument("--entry", action="append", default=[],
                   help="extra function NAME treated as trace-destined "
                        "(repeatable)")
    p.add_argument("--no-concurrency", action="store_true",
                   help="skip level 4 (lock-order / blocking-under-lock / "
                        "unregistered-thread); by default the concurrency "
                        "pass runs over the same paths")
    p.add_argument("--rules", default="",
                   help="comma-separated rule ids: report only these")
    p.add_argument("--disable", default="",
                   help="comma-separated rule ids to skip")
    p.add_argument("--fail-on", default="error",
                   choices=["info", "warning", "error", "never"],
                   help="exit 1 when a finding at/above this severity "
                        "exists (default: error)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable JSON on stdout")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    return p


def _filter(findings: List[Finding], only: str, disable: str
            ) -> List[Finding]:
    keep = {r.strip() for r in only.split(",") if r.strip()}
    drop = {r.strip() for r in disable.split(",") if r.strip()}
    out = findings
    if keep:
        out = [f for f in out if f.rule in keep]
    if drop:
        out = [f for f in out if f.rule not in drop]
    return out


def main(argv: Optional[List[str]] = None,
         stdout=None) -> int:
    out = stdout or sys.stdout
    parser = _build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.id:<18} {r.severity:<8} {r.doc}", file=out)
        return 0

    try:
        findings, n_files = lint_paths(args.paths,
                                       all_functions=args.all_functions,
                                       entries=args.entry)
        if not args.no_concurrency:
            from .concurrency import analyze_paths
            findings += analyze_paths(args.paths)[0]
    except FileNotFoundError as e:
        print(f"tpu-lint: no such path: {e.args[0]}", file=sys.stderr)
        return 2

    findings = _filter(findings, args.rules, args.disable)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    counts = {"error": 0, "warning": 0, "info": 0}
    for f in findings:
        counts[f.severity] += 1

    if args.as_json:
        json.dump({"version": 1, "files": n_files,
                   "counts": counts,
                   "findings": [f.as_dict() for f in findings]},
                  out, indent=1)
        out.write("\n")
    else:
        for f in findings:
            print(f.format(), file=out)
        print(f"tpu-lint: {len(findings)} finding(s) "
              f"({counts['error']} error, {counts['warning']} warning, "
              f"{counts['info']} info) in {n_files} file(s)", file=out)

    if args.fail_on != "never" and any(
            severity_at_least(f.severity, args.fail_on) for f in findings):
        return 1
    return 0
