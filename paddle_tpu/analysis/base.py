"""tpu-lint core: findings, the rule registry, and suppression parsing.

Reference parity: the IR-pass analysis half of `paddle/fluid/framework/ir/`
(graph pattern detectors like `ir/identity_op_clean_pass`,
`ir/delete_op_device_pass`'s graph walks) plus the API-misuse checks the
reference scatters through `enforce`/op-kernel preconditions. TPU-native
redesign: the hazards worth detecting are the ones that break the
trace -> ProgramDesc -> HLO path (host syncs, retrace storms, collective
deadlocks), and all of them are visible STATICALLY — in the Python AST,
the traced jaxpr, or the StableHLO module — so they are reported before a
pod slice ever hangs.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

__all__ = ["Severity", "Rule", "RULES", "Finding", "Suppressions",
           "severity_at_least"]


class Severity:
    INFO = "info"
    WARNING = "warning"
    ERROR = "error"

    _ORDER = {"info": 0, "warning": 1, "error": 2}


def severity_at_least(sev: str, threshold: str) -> bool:
    return Severity._ORDER[sev] >= Severity._ORDER[threshold]


class Rule:
    __slots__ = ("id", "severity", "doc")

    def __init__(self, id_: str, severity: str, doc: str):
        self.id, self.severity, self.doc = id_, severity, doc

    def __repr__(self):
        return f"Rule({self.id}, {self.severity})"


# The rule table (README "Static analysis" section mirrors this).
RULES: Dict[str, Rule] = {r.id: r for r in [
    # -- source rules (analysis/lint.py, AST level) --
    Rule("host-sync", Severity.ERROR,
         ".numpy()/.item()/.tolist()/float()/int()/bool() on a tensor in a "
         "traced region — a device->host sync; under trace it raises "
         "ConcretizationError or silently pins the step on the host"),
    Rule("tensor-branch", Severity.ERROR,
         "Python `if`/`while`/`assert` on a tensor value — data-dependent "
         "control flow cannot be traced (use lax.cond semantics via "
         "static.nn.cond, or keep the predicate host-static)"),
    Rule("traced-print", Severity.WARNING,
         "print() inside a traced region — runs once at trace time, never "
         "per step; use jax.debug.print / monitor counters"),
    Rule("stdlib-random", Severity.ERROR,
         "stdlib random.* / numpy.random.* inside a traced region — the "
         "value is burned in at trace time, breaking the carried-key RNG "
         "regime (use paddle randomness ops, which ride the trace key)"),
    Rule("shape-capture", Severity.WARNING,
         "branching on a tensor's .shape/len() — each distinct input shape "
         "silently compiles a different program (a per-shape retrace fork)"),
    Rule("fused-update", Severity.INFO,
         "advisory (--all scans): a per-parameter Python loop doing array "
         "math inside an eager step/update function dispatches one "
         "executable per parameter — fuse it into one jitted tree-level "
         "update (optimizer.Optimizer._make_fused_update pattern); loops "
         "inside traced regions unroll into one executable and are exempt"),
    Rule("lazy-sync", Severity.INFO,
         "advisory: a host-sync call (.numpy()/.item()/.tolist()/float()/"
         "int()/bool()) inside a loop body — under FLAGS_lazy_eager every "
         "iteration flushes the pending lazy segment, re-serializing "
         "dispatch the executor was batching; hoist the sync out of the "
         "hot loop (or accumulate on device and sync once after it)"),
    Rule("raw-socket", Severity.WARNING,
         "socket.recv/sendall/create_connection outside utils/net.py — "
         "raw wire I/O bypasses the unified RPC substrate (deadlines, "
         "retries, auth/TLS, fault sites, wire-health counters); route "
         "through RpcChannel/RpcServer or the net.py helpers"),
    Rule("buffer-retain", Severity.INFO,
         "advisory: a self./cls. attribute assigned from a per-step tensor "
         "inside a loop body — the held reference outlives the step, "
         "defeats buffer donation, and pins device memory until the next "
         "overwrite (the creeping 'other' bytes a mem census shows); keep "
         "a host scalar (float(loss)) or np.asarray copy instead"),
    # -- graph rules (analysis/graph.py, jaxpr/Program level) --
    Rule("dead-op", Severity.WARNING,
         "op whose results are never used by any program output — wasted "
         "trace/compile time and a likely logic error"),
    Rule("unused-var", Severity.WARNING,
         "program input consumed by no live op — dead argument traffic"),
    Rule("dtype-widen", Severity.ERROR,
         "implicit f32/bf16 -> f64 (or c64 -> c128) widening — float64 is "
         "emulated on TPU and wrecks step time"),
    Rule("host-callback", Severity.WARNING,
         "host callback op inside the compiled program — a device->host "
         "round trip on every step"),
    Rule("collective-order", Severity.ERROR,
         "ranks/stages issue diverging static collective sequences — the "
         "pod deadlocks at the first mismatched collective at runtime"),
    Rule("stage-graph", Severity.ERROR,
         "pipeline stage wiring broken: a stage's output cannot feed the "
         "next stage, or a stage has no owner — the pipeline hangs"),
    # -- concurrency rules (analysis/concurrency.py, whole-package AST) --
    Rule("lock-order", Severity.ERROR,
         "two code paths acquire the same pair of locks in opposite "
         "orders — two threads running them concurrently deadlock; the "
         "finding names both sites of the cycle"),
    Rule("blocking-under-lock", Severity.ERROR,
         "unbounded blocking (socket recv/accept, queue.get/join/wait "
         "with no timeout, long time.sleep, RPC call_with_retry) inside "
         "a held-lock region — every contending thread stalls for the "
         "full blocking duration; move it out or bound it"),
    Rule("unregistered-thread", Severity.WARNING,
         "raw threading.Thread() outside the syncwatch ThreadRegistry — "
         "invisible to the leak fixtures and the `monitor threads` "
         "table; spawn via syncwatch.Thread(..., owner=__name__)"),
]}


class Finding:
    """One diagnostic. `path`/`line` anchor it; `func` names the traced
    function (or program/rank) it was found in."""

    __slots__ = ("rule", "severity", "path", "line", "col", "message", "func")

    def __init__(self, rule: str, message: str, path: str = "<program>",
                 line: int = 0, col: int = 0, func: str = "",
                 severity: Optional[str] = None):
        self.rule = rule
        self.severity = severity or RULES[rule].severity
        self.path, self.line, self.col = path, line, col
        self.message = message
        self.func = func

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        where = f" (in {self.func})" if self.func else ""
        return f"{loc}: {self.severity}: {self.message}{where} [{self.rule}]"

    def as_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message, "func": self.func}

    def __repr__(self):
        return f"Finding({self.format()})"


# `# tpu-lint: disable=rule-a,rule-b` — on a code line it silences those
# rules for that line; on a comment-only line it silences them for the
# whole file. `disable=all` silences everything.
_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([\w,\-]+)")


class Suppressions:
    """Parsed suppression comments for one source file."""

    def __init__(self, source: str):
        self.by_line: Dict[int, set] = {}
        self.file_wide: set = set()
        for i, line in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if line.strip().startswith("#"):
                self.file_wide |= rules
            else:
                self.by_line.setdefault(i, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if "all" in self.file_wide or rule in self.file_wide:
            return True
        here = self.by_line.get(line, ())
        return "all" in here or rule in here

    def apply(self, findings: List[Finding]) -> List[Finding]:
        return [f for f in findings
                if not self.suppressed(f.rule, f.line)]
