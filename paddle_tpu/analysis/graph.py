"""tpu-lint level 2: graph analysis over traced jaxprs / static Programs.

Reference parity: the analysis half of the IR-pass framework
(`paddle/fluid/framework/ir/` graph walks; `static/passes.py` mirrors the
rewrite half). The traced jaxpr is the SSA graph here: dead-op liveness,
implicit dtype widenings, host callbacks, and — the headline rule —
collective-ordering verification: extract each rank's/pipeline stage's
STATIC sequence of collectives (op, axis, shape, dtype) and prove the
sequences match, naming the first divergence instead of letting the pod
deadlock at runtime.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

import numpy as np

from .base import Finding

__all__ = ["iter_eqns", "live_eqn_mask", "dead_eqns",
           "analyze_jaxpr", "analyze_program",
           "CollectiveDesc", "collective_sequence", "verify_collective_order",
           "verify_stage_chain", "verify_stage_assignment"]

# jax primitives that are cross-device collectives: a rank that reaches one
# of these blocks until every peer on the axis reaches the SAME one.
# psum2 is shard_map's check_rep rewrite of psum (same wire op); its
# companion pbroadcast is a replication-accounting marker that lowers to
# nothing, so it is deliberately NOT a collective here — otherwise the
# same program would sign differently under check_rep=True vs False.
COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmax", "pmin", "pmean", "ppermute",
    "all_gather", "all_to_all", "psum_scatter", "reduce_scatter", "pgather",
}
_CANONICAL_OP = {"psum2": "psum"}

# primitives that re-enter the host from inside the compiled program
HOST_CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "callback", "debug_callback",
    "debug_print", "host_callback_call", "outside_call",
}


def _sub_jaxprs(params: Mapping[str, Any]):
    """Jaxprs nested in an eqn's params (cond branches, scan/while bodies,
    pjit/shard_map/remat jaxprs) — `static/passes.py` uses the same shape."""
    for v in params.values():
        for c in (v if isinstance(v, (tuple, list)) else (v,)):
            if hasattr(c, "jaxpr"):          # ClosedJaxpr
                yield c.jaxpr
            elif hasattr(c, "eqns"):         # plain Jaxpr
                yield c


def iter_eqns(jaxpr) -> Iterable:
    """Every eqn in program order, recursing into nested regions (pjit,
    shard_map, scan/while/cond bodies — bodies yield their eqns once)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _as_jaxpr(obj, specs: Optional[Sequence] = None):
    """Normalize callable/Program/(Closed)Jaxpr to a plain Jaxpr."""
    import jax
    if hasattr(obj, "jaxpr"):                     # ClosedJaxpr
        return obj.jaxpr
    if hasattr(obj, "eqns"):                      # plain Jaxpr
        return obj
    if hasattr(obj, "_fn") and hasattr(obj, "_arg_specs"):   # static.Program
        return jax.make_jaxpr(obj._fn)(*obj._arg_specs).jaxpr
    if callable(obj):
        if specs is None:
            raise ValueError("collective/graph analysis of a callable needs "
                             "example args or ShapeDtypeStructs (specs)")
        return jax.make_jaxpr(obj)(*specs).jaxpr
    raise TypeError(f"cannot analyze {type(obj).__name__}")


# ---- liveness (dead-op / unused-var) ---------------------------------------

def live_eqn_mask(jaxpr) -> List[bool]:
    """Per-eqn liveness at this jaxpr level: an eqn is live when any of its
    outputs feeds a live eqn or a program output, or it carries effects
    (donation/io/debug). Nested bodies are treated atomically."""
    live_vars = {id(v) for v in jaxpr.outvars}
    mask = [False] * len(jaxpr.eqns)
    for i in range(len(jaxpr.eqns) - 1, -1, -1):
        eqn = jaxpr.eqns[i]
        effectful = bool(getattr(eqn, "effects", ()))
        if effectful or any(id(v) in live_vars for v in eqn.outvars):
            mask[i] = True
            for v in eqn.invars:
                live_vars.add(id(v))
    return mask


def dead_eqns(jaxpr) -> Iterable:
    """Dead eqns at every nesting level: a locally-dead eqn (value never
    reaches its own jaxpr's outputs) is globally dead no matter how the
    enclosing program uses that jaxpr — so pjit/shard_map/remat wrappers
    (e.g. a to_static capture, which is ONE pjit eqn at top level) are
    descended through. Eqns inside an already-dead region are skipped:
    the region itself is the finding."""
    mask = live_eqn_mask(jaxpr)
    for eqn, live in zip(jaxpr.eqns, mask):
        if not live:
            yield eqn
        else:
            for sub in _sub_jaxprs(eqn.params):
                yield from dead_eqns(sub)


def analyze_jaxpr(jaxpr, path: str = "<program>",
                  func: str = "") -> List[Finding]:
    """dead-op / unused-var / dtype-widen / host-callback over one traced
    program. `jaxpr` may be a Jaxpr, ClosedJaxpr, static.Program, or a
    callable (then pass specs via analyze_program/collective helpers)."""
    jaxpr = _as_jaxpr(jaxpr)
    findings: List[Finding] = []
    mask = live_eqn_mask(jaxpr)

    used = set()
    for eqn, live in zip(jaxpr.eqns, mask):
        if live:
            used.update(id(v) for v in eqn.invars)
    used.update(id(v) for v in jaxpr.outvars)

    for eqn in dead_eqns(jaxpr):
        findings.append(Finding(
            "dead-op",
            f"dead op '{eqn.primitive.name}': its results are never "
            "used by any program output", path=path, func=func))

    for i, v in enumerate(jaxpr.invars):
        if id(v) not in used:
            findings.append(Finding(
                "unused-var",
                f"program input #{i} ({v.aval.str_short()}) is consumed by "
                "no live op", path=path, func=func))

    def _wide(dt) -> bool:
        try:
            d = np.dtype(dt)
        except TypeError:
            return False        # extension dtypes (PRNG keys) are never wide
        return d in (np.dtype("float64"), np.dtype("complex128"))

    for eqn in iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim in HOST_CALLBACK_PRIMS:
            findings.append(Finding(
                "host-callback",
                f"host callback '{prim}' inside the compiled program — a "
                "device->host round trip every step", path=path, func=func))
        in_dts = [v.aval.dtype for v in eqn.invars
                  if hasattr(v.aval, "dtype")]
        out_dts = [v.aval.dtype for v in eqn.outvars
                   if hasattr(v.aval, "dtype")]
        if out_dts and any(_wide(d) for d in out_dts) \
                and in_dts and not any(_wide(d) for d in in_dts):
            findings.append(Finding(
                "dtype-widen",
                f"'{prim}' widens {in_dts[0]} -> "
                f"{next(d for d in out_dts if _wide(d))} (float64 is "
                "emulated on TPU)", path=path, func=func))
    return findings


def analyze_program(program, path: Optional[str] = None) -> List[Finding]:
    """Graph rules over a `static.Program` (traces its captured fn)."""
    return analyze_jaxpr(program, path=path or f"<Program {program.name}>",
                         func=program.name)


# ---- collective-ordering verification --------------------------------------

class CollectiveDesc:
    """One collective in a rank's static sequence: what must match across
    peers for the op to complete instead of deadlocking."""

    __slots__ = ("op", "axis", "shape", "dtype", "perm")

    def __init__(self, op: str, axis, shape, dtype, perm=None):
        self.op = op
        self.axis = axis
        self.shape = tuple(shape)
        self.dtype = str(dtype)
        self.perm = tuple(perm) if perm is not None else None

    def __eq__(self, other):
        return isinstance(other, CollectiveDesc) and \
            (self.op, self.axis, self.shape, self.dtype, self.perm) == \
            (other.op, other.axis, other.shape, other.dtype, other.perm)

    def __hash__(self):
        return hash((self.op, self.axis, self.shape, self.dtype, self.perm))

    def __repr__(self):
        shp = ",".join(str(s) for s in self.shape)
        return f"{self.op}(axis={self.axis}, {self.dtype}[{shp}])"


def _axis_of(params: Mapping[str, Any]):
    ax = params.get("axis_name", params.get("axes"))
    if isinstance(ax, (tuple, list)):
        return ax[0] if len(ax) == 1 else tuple(ax)
    return ax


def collective_sequence(obj, *specs) -> List[CollectiveDesc]:
    """The static, ordered collective sequence of a program. `obj` may be a
    (Closed)Jaxpr, static.Program, callable (+ example args/specs), or an
    already-extracted sequence (returned as-is). Collectives inside
    scan/while/cond bodies appear once, in body order — peers trace the
    same structure, so the comparison stays sound."""
    if isinstance(obj, (list, tuple)) and \
            all(isinstance(c, CollectiveDesc) for c in obj):
        return list(obj)
    jaxpr = _as_jaxpr(obj, specs if specs else None)
    seq: List[CollectiveDesc] = []
    for eqn in iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim not in COLLECTIVE_PRIMS:
            continue
        avals = [v.aval for v in eqn.invars if hasattr(v.aval, "shape")]
        shape = avals[0].shape if avals else ()
        dtype = avals[0].dtype if avals else ""
        seq.append(CollectiveDesc(_CANONICAL_OP.get(prim, prim),
                                  _axis_of(eqn.params), shape, dtype,
                                  perm=eqn.params.get("perm")))
    return seq


def verify_collective_order(programs: Mapping[str, Any],
                            specs: Optional[Mapping[str, Sequence]] = None
                            ) -> List[Finding]:
    """Prove every rank's/stage's static collective sequence matches the
    first entry's (the reference rank). Values may be sequences from
    `collective_sequence`, Programs, jaxprs, or callables (give per-name
    example args via `specs`). Returns findings naming the FIRST
    divergence — the exact op the pod would deadlock on."""
    names = list(programs)
    if len(names) < 2:
        return []
    seqs: Dict[str, List[CollectiveDesc]] = {}
    for n in names:
        sp = (specs or {}).get(n, ())
        seqs[n] = collective_sequence(programs[n], *sp)
    ref_name, ref = names[0], seqs[names[0]]
    findings: List[Finding] = []
    for n in names[1:]:
        seq = seqs[n]
        for i, (a, b) in enumerate(zip(ref, seq)):
            if a != b:
                findings.append(Finding(
                    "collective-order",
                    f"{n} diverges from {ref_name} at collective #{i}: "
                    f"{ref_name} issues {a!r}, {n} issues {b!r} — the pod "
                    "deadlocks here at runtime", func=n))
                break
        else:
            if len(ref) != len(seq):
                short, long_ = (n, ref_name) if len(seq) < len(ref) \
                    else (ref_name, n)
                i = min(len(ref), len(seq))
                extra = (ref if len(ref) > len(seq) else seq)[i]
                findings.append(Finding(
                    "collective-order",
                    f"{n} issues {len(seq)} collectives, {ref_name} issues "
                    f"{len(ref)}: {short} never reaches {long_}'s "
                    f"collective #{i} ({extra!r}) — peers block there "
                    "forever", func=n))
    return findings


# ---- pipeline/task-graph verification --------------------------------------

def verify_stage_chain(stages: Sequence, sample) -> List[Finding]:
    """Prove each pipeline stage's output can feed the next stage by
    abstract evaluation (no FLOPs): names the first broken edge instead of
    letting the fleet executor hang mid-drain. `sample` is a stage-0
    example input (array or ShapeDtypeStruct)."""
    import jax

    findings: List[Finding] = []
    x = sample
    for i, stage in enumerate(stages):
        try:
            x = jax.eval_shape(stage, x)
        except Exception as e:
            src = "microbatch input" if i == 0 else f"stage {i - 1} output"
            shp = jax.tree_util.tree_map(
                lambda a: getattr(a, "shape", None), x)
            findings.append(Finding(
                "stage-graph",
                f"stage {i} cannot consume {src} {shp}: "
                f"{type(e).__name__}: {e}", func=f"stage{i}"))
            return findings
    return findings


def verify_stage_assignment(stage_owner: Mapping[int, int], n_stages: int,
                            my_rank: Optional[int] = None,
                            my_stages: Optional[Iterable[int]] = None
                            ) -> List[Finding]:
    """Fleet-executor task-graph ownership check: every stage 0..n-1 must
    have an owner, and a rank must only host stages it owns — a stage with
    no owner is a pipeline that never drains."""
    findings: List[Finding] = []
    for s in range(n_stages):
        if s not in stage_owner:
            findings.append(Finding(
                "stage-graph",
                f"stage {s} has no owning rank: microbatches reaching it "
                "are never consumed", func=f"stage{s}"))
    for s in stage_owner:
        if not (0 <= s < n_stages):
            findings.append(Finding(
                "stage-graph",
                f"stage_owner maps nonexistent stage {s} "
                f"(n_stages={n_stages})", func=f"stage{s}"))
    if my_rank is not None and my_stages is not None:
        for s in my_stages:
            owner = stage_owner.get(s)
            if owner is not None and owner != my_rank:
                findings.append(Finding(
                    "stage-graph",
                    f"rank {my_rank} hosts stage {s} but stage_owner maps "
                    f"it to rank {owner}: both ranks will consume its "
                    "messages", func=f"stage{s}"))
    return findings
