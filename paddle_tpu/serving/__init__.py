"""paddle_tpu.serving — the TPU-native serving plane.

Sits between the wire protocols (`inference/server.py`, `csrc/
predict_capi.cpp`) and the Predictor: a `ServingEngine` coalesces
concurrent requests into padded shape-bucket batches (declared or
learned, warmed up so steady-state serving never compiles), enforces
per-request deadlines and queue-depth backpressure, drains gracefully on
shutdown, and reports health + `paddle_tpu.monitor` metrics.

Reference parity: the deployment role of `paddle/fluid/inference/`
(AnalysisPredictor served under Paddle Serving / Triton-style dynamic
batching); see README "Serving" for configuration and overload semantics.
"""
from .bucket import BucketSet, ShapeBucket, default_batch_sizes, signature_of  # noqa: F401
from .engine import (  # noqa: F401
    DeadlineExceededError, EngineConfig, EngineStoppedError, NoBucketError,
    ResponseFuture, ServerOverloadedError, ServingEngine, ServingError,
)
from .fleet import (  # noqa: F401  (after engine: fleet builds on it)
    FleetError, FleetRouter, HBMBudgetExceededError, ModelTenant,
    NoHealthyReplicaError, ReplicaAgent, RolloutResult, SequenceLedger,
)
from .autoscaler import (  # noqa: F401  (after fleet: the control plane)
    Autoscaler, DecisionLedger, ReplicaPool, ScaleDecision, ScalePolicy,
)
from .online import (  # noqa: F401  (the online-learning serving plane)
    OnlineRollbackGuard, OnlineServingTable, StalenessExceededError,
    load_serving_tables, save_serving_generation,
)
from .llm import LLMConfig, LLMEngine, LLMStream  # noqa: F401

__all__ = [
    "LLMEngine", "LLMConfig", "LLMStream",
    "ServingEngine", "EngineConfig", "ResponseFuture",
    "ShapeBucket", "BucketSet", "default_batch_sizes", "signature_of",
    "ServingError", "ServerOverloadedError", "DeadlineExceededError",
    "EngineStoppedError", "NoBucketError",
    "FleetRouter", "ReplicaAgent", "ModelTenant", "SequenceLedger",
    "RolloutResult", "FleetError", "NoHealthyReplicaError",
    "HBMBudgetExceededError",
    "Autoscaler", "ScalePolicy", "ScaleDecision", "ReplicaPool",
    "DecisionLedger",
    "OnlineServingTable", "OnlineRollbackGuard", "StalenessExceededError",
    "save_serving_generation", "load_serving_tables",
]
