"""Online-learning serving plane: staleness-bounded embedding tables.

`OnlineServingTable` answers embedding lookups inside the Predictor
path from rows streamed off the trainer-side PS by the delta-push plane
(`distributed/ps/delta.py`). It tracks how stale it is — the time since
the last SUCCESSFUL delta sync, where "no rows changed" counts as a
sync — and refuses (or loudly degrades) lookups past
`FLAGS_online_max_staleness_s`: serving silently-stale recommendations
is the failure mode this plane exists to prevent.

Versioned cutover rides the guard checkpoint machinery
(`guard/checkpoint.py`): `save_serving_generation` writes the table
rows as a guard-state generation, so a `ModelTenant` hosting the CTR
model reloads ('PDMV' reload) and instantly rolls back ('PDMV'
rollback -> guard `.bak`) through the exact paths the fleet already
chaos-tests. `OnlineRollbackGuard` closes that loop: a probe batch is
validated every interval and a poisoned generation (non-finite or
out-of-range predictions) triggers the fleet-wide rollback within one
interval, recorded in a DecisionLedger-style entry plus a telemetry
event.

Gauges (PR 16 telemetry plane picks these up from the monitor
registry): `online.<table>.staleness_s`, `online.<table>.applied_version`,
`online.<table>.rows`. Counters: `online.stale_serves`,
`online.stale_rejects`, `online.poison_rows`, `online.rollbacks`.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, Dict, Optional

import numpy as np

from .. import monitor as _monitor
from ..core import flags as _flags
from ..guard.checkpoint import save_guard_state
from ..utils import syncwatch as _syncwatch

__all__ = ["StalenessExceededError", "OnlineServingTable",
           "save_serving_generation", "load_serving_tables",
           "OnlineRollbackGuard"]

# live rollback guards, for the conftest leak fixture
_LIVE = weakref.WeakSet()


class StalenessExceededError(RuntimeError):
    """Lookup refused: the table is staler than the configured bound
    and `FLAGS_online_staleness_degrade` is 'reject'."""


class OnlineServingTable:
    """Serving-side mirror of one PS sparse table: embedding VALUES
    only (optimizer slots never leave the trainer plane), installed by
    a `DeltaSubscriber`, read by the prediction handler.

    Unknown/cold keys read as zeros — a key the trainer has pulled but
    never pushed carries no trained signal yet, and a fixed answer
    beats an unbounded wait. Installs are idempotent value writes, so
    a re-pulled delta after a torn response changes nothing.
    """

    def __init__(self, name: str, dim: int,
                 max_staleness_s: Optional[float] = None,
                 degrade: Optional[str] = None):
        self.name = name
        self.dim = int(dim)
        self._lock = threading.Lock()
        self._rows: Dict[int, np.ndarray] = {}
        self.applied_version = -1
        self._fresh_t: Optional[float] = None   # monotonic of last sync
        self._max_staleness_s = max_staleness_s
        self._degrade = degrade
        self._stale_episode = False   # one telemetry event per episode
        self._installs = 0
        self._poison_rows = 0

    # ---- install side (DeltaSubscriber contract) ----
    def install_delta(self, batch) -> None:
        """Apply one `delta.DeltaBatch`: merge live rows + drop
        tombstoned keys, or replace the whole table when the batch is a
        full resync. Non-finite rows are installed but counted — the
        rollback guard, not the install path, owns the poison verdict
        (a half-installed table would be a worse failure mode than a
        loudly-poisoned one)."""
        rows = np.asarray(batch.rows, np.float32)
        bad = int(np.sum(~np.isfinite(rows).all(axis=1))) if len(rows) else 0
        with self._lock:
            if batch.full:
                self._rows = {}
            for i, k in enumerate(batch.live_keys):
                self._rows[int(k)] = rows[i].copy()
            for k in batch.dead_keys:
                self._rows.pop(int(k), None)
            self.applied_version = int(batch.version)
            self._installs += 1
            self._poison_rows += bad
        if _monitor._ENABLED:
            if bad:
                _monitor.count("online.poison_rows", bad)
            _monitor.gauge_set(f"online.{self.name}.applied_version",
                               self.applied_version)
            _monitor.gauge_set(f"online.{self.name}.rows", len(self._rows))

    def mark_fresh(self) -> None:
        """Record a successful sync (even an empty delta: 'nothing
        changed' is freshness, not staleness)."""
        self._fresh_t = time.monotonic()
        self._stale_episode = False
        if _monitor._ENABLED:
            _monitor.gauge_set(f"online.{self.name}.staleness_s", 0.0)

    # ---- read side (prediction handler contract) ----
    def staleness_s(self) -> float:
        if self._fresh_t is None:
            return float("inf")
        return time.monotonic() - self._fresh_t

    def _staleness_bound(self) -> float:
        if self._max_staleness_s is not None:
            return float(self._max_staleness_s)
        return float(_flags.flag("online_max_staleness_s"))

    def lookup(self, ids) -> np.ndarray:
        """[n] ids -> [n, dim] f32 rows (zeros for cold keys). Past the
        staleness bound the configured degrade applies — NEVER a silent
        stale answer: 'serve_stale' serves but counts + emits one
        telemetry event per stale episode, 'reject' raises."""
        stale = self.staleness_s()
        if stale > self._staleness_bound():
            degrade = (self._degrade if self._degrade is not None
                       else str(_flags.flag("online_staleness_degrade")))
            if _monitor._ENABLED:
                _monitor.gauge_set(f"online.{self.name}.staleness_s", stale)
            if degrade == "reject":
                if _monitor._ENABLED:
                    _monitor.count("online.stale_rejects")
                raise StalenessExceededError(
                    f"online table {self.name!r} is {stale:.3f}s stale "
                    f"(bound {self._staleness_bound()}s)")
            if _monitor._ENABLED:
                _monitor.count("online.stale_serves")
            if not self._stale_episode:
                self._stale_episode = True
                from ..obs import telemetry as _telemetry
                _telemetry.emit("online_stale_serve", table=self.name,
                                staleness_s=round(stale, 3),
                                version=self.applied_version)
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.zeros((len(ids), self.dim), np.float32)
        with self._lock:
            for r, i in enumerate(ids):
                row = self._rows.get(int(i))
                if row is not None:
                    out[r] = row
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def stats(self) -> dict:
        with self._lock:
            n = len(self._rows)
        s = self.staleness_s()
        return {"table": self.name, "dim": self.dim, "rows": n,
                "applied_version": self.applied_version,
                "staleness_s": None if s == float("inf") else round(s, 4),
                "installs": self._installs,
                "poison_rows": self._poison_rows}

    # ---- guard-generation cutover ----
    def export_arrays(self) -> Dict[str, np.ndarray]:
        with self._lock:
            keys = np.fromiter(self._rows.keys(), np.int64,
                               len(self._rows))
            order = np.argsort(keys, kind="stable")
            keys = keys[order]
            vals = (np.stack([self._rows[int(k)] for k in keys])
                    if len(keys) else np.zeros((0, self.dim), np.float32))
        return {f"{self.name}::keys": keys,
                f"{self.name}::rows": vals.astype(np.float32)}

    def load_arrays(self, keys: np.ndarray, rows: np.ndarray,
                    version: int) -> None:
        """Replace content from a guard generation (tenant reload and
        'PDMV' rollback both land here)."""
        rows = np.asarray(rows, np.float32)
        with self._lock:
            self._rows = {int(k): rows[i].copy()
                          for i, k in enumerate(np.asarray(keys).reshape(-1))}
            self.applied_version = int(version)
        self.mark_fresh()


def save_serving_generation(dirname: str,
                            tables: Dict[str, OnlineServingTable],
                            meta_extra: Optional[dict] = None) -> str:
    """Persist the tables as ONE guard-state generation (atomic_write +
    CRC manifest + `.bak` of the previous generation), so tenant
    reload/rollback flows through `guard/checkpoint.py` untouched."""
    arrays: Dict[str, np.ndarray] = {}
    meta_tables: Dict[str, dict] = {}
    for name, t in tables.items():
        arrays.update(t.export_arrays())
        meta_tables[name] = {"dim": t.dim,
                             "version": int(t.applied_version)}
    meta = dict(meta_extra or {}, online_tables=meta_tables)
    return save_guard_state(dirname, arrays, meta)


def load_serving_tables(arrays: Dict[str, np.ndarray],
                        meta: dict, **table_kw
                        ) -> Dict[str, OnlineServingTable]:
    """Rebuild the tables from a guard generation — the piece a
    `ModelTenant.handler_factory` calls so reload AND rollback rebuild
    the serving state from whatever generation the guard files hold."""
    out: Dict[str, OnlineServingTable] = {}
    for name, tm in (meta.get("online_tables") or {}).items():
        t = OnlineServingTable(name, int(tm["dim"]), **table_kw)
        t.load_arrays(arrays.get(f"{name}::keys", np.zeros(0, np.int64)),
                      arrays.get(f"{name}::rows",
                                 np.zeros((0, int(tm["dim"])), np.float32)),
                      int(tm.get("version", 0)))
        out[name] = t
    return out


class OnlineRollbackGuard:
    """Poisoned-generation watchdog: every `interval_s` it runs
    `probe_fn()` (a validation prediction batch) and, when the output
    is non-finite or leaves `bounds`, fires `rollback_fn()` — e.g.
    `FleetRouter.rollback_model` — so the bad generation is off the
    serving path within ONE probe interval. Every decision lands in a
    DecisionLedger-style record (action / reason / evidence / outcome)
    and a telemetry event, mirroring the autoscaler's discipline."""

    def __init__(self, probe_fn: Callable[[], np.ndarray],
                 rollback_fn: Callable[[], object],
                 interval_s: float = 0.5,
                 bounds: tuple = (0.0, 1.0),
                 max_ledger: int = 256):
        self.probe_fn = probe_fn
        self.rollback_fn = rollback_fn
        self.interval_s = float(interval_s)
        self.bounds = bounds
        import collections
        self.ledger: "collections.deque" = collections.deque(
            maxlen=max_ledger)
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rollbacks = 0
        _LIVE.add(self)

    def _record(self, action: str, reason: str, evidence: dict,
                outcome: str) -> dict:
        self._seq += 1
        entry = {"seq": self._seq, "ts": time.time(), "action": action,
                 "reason": reason, "evidence": evidence, "outcome": outcome}
        self.ledger.append(entry)
        return entry

    def check_once(self) -> bool:
        """One probe -> verdict; returns True when a rollback fired."""
        try:
            preds = np.asarray(self.probe_fn(), np.float64).reshape(-1)
        except Exception as e:  # a dead probe is a verdict, not a crash
            self._record("probe", f"probe failed: {type(e).__name__}",
                         {"error": str(e)}, "skipped")
            return False
        lo, hi = self.bounds
        finite = bool(np.isfinite(preds).all()) if len(preds) else True
        in_range = bool(((preds >= lo) & (preds <= hi)).all()) \
            if finite and len(preds) else finite
        if finite and in_range:
            return False
        fin = preds[np.isfinite(preds)]
        evidence = {"n": int(len(preds)),
                    "non_finite": int((~np.isfinite(preds)).sum()),
                    "min": float(fin.min()) if len(fin) else None,
                    "max": float(fin.max()) if len(fin) else None}
        reason = ("non-finite predictions" if not finite
                  else f"predictions outside [{lo}, {hi}]")
        try:
            result = self.rollback_fn()
            outcome = f"rolled_back:{result}"
        except Exception as e:
            outcome = f"rollback_failed:{type(e).__name__}"
        self.rollbacks += 1
        self._record("rollback", reason, evidence, outcome)
        if _monitor._ENABLED:
            _monitor.count("online.rollbacks")
        from ..obs import telemetry as _telemetry
        _telemetry.emit("online_rollback", reason=reason, **evidence)
        return True

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.check_once()

    def start(self) -> "OnlineRollbackGuard":
        self._thread = _syncwatch.Thread(target=self._loop, daemon=True,
                                        name="online-guard")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    close = stop   # the conftest reaper speaks close()
