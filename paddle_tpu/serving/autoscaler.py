"""SLO-driven elastic autoscaler — the fleet control plane's actuator.

PR 16's telemetry plane finished the SENSOR side (fleet-wide burn/queue
gauges, death/drain events, true fleet quantiles at one collector); this
module closes the sense→decide→act loop:

    TelemetryCollector ──sense──> Autoscaler ──decide──> ScalePolicy
                                      │
                                      └──act──> ReplicaPool
                                                  ├─ scale OUT: spawn a
                                                  │  ReplicaAgent (warm-
                                                  │  started in seconds
                                                  │  with ZERO compiles
                                                  │  via the persistent
                                                  │  compile cache)
                                                  ├─ scale IN: graceful
                                                  │  'PDDR' drain + lease
                                                  │  reclaim
                                                  └─ scale-to-zero: idle
                                                     tenants evicted via
                                                     the HBM-budget LRU

Three pieces, deliberately separable:

  - `ScalePolicy` — PURE decision math, no I/O and injectable clock, so
    hysteresis/cooldown/scale-to-zero are table-testable from traces
    alone. Scale out when the worst replica's shortest-window burn or
    the fleet queue fraction crosses the high thresholds; the idle clock
    only runs while BOTH signals sit below the low thresholds (the gap
    between is the hysteresis band where nothing happens); per-direction
    cooldowns bound the actuation rate; a blind policy (collector dead,
    zero alive sources) holds steady.
  - `ReplicaPool` — the actuator over FleetRouter + a `spawn` callable.
    A spawned replica must answer its first 'PDHQ' within
    `FLAGS_autoscaler_spawn_timeout_s` or it is reaped — handle killed,
    store record + elastic lease reclaimed via `FleetRouter.forget` —
    and counted `autoscaler.spawn_failures`; it is never routed to
    forever. Scale-in drains gracefully; a SIGKILL landing mid-drain
    still converges (the connection error is the verdict, the corpse's
    lease is reclaimed, the ledger records `died_during_drain`).
  - `DecisionLedger` — every scale action with its triggering evidence,
    in a bounded ring: dumped into the flight recorder
    (`Autoscaler.dump`) and rendered by `monitor top` (the collector's
    pool row). When scale-out cannot be satisfied (spawn retry budget
    exhausted, HBM refused) the collector's built-in `scale_blocked`
    alert fires once per transition.

Failure → behavior: collector dead → hold steady; spawn fails → alert +
retry budget (one retry per cooldown after exhaustion); drain
interrupted by SIGKILL → pool consistent, lease reclaimed, ledger audit
clean. Chaos-tested in tests/test_autoscaler_chaos.py.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .. import monitor as _monitor
from .. import obs as _obs
from ..core import flags as _flags
from .fleet import FleetError
from ..utils import syncwatch as _syncwatch

__all__ = ["Autoscaler", "ScalePolicy", "ScaleDecision", "ReplicaPool",
           "DecisionLedger"]

# unclosed autoscalers, so the test-suite leak fixture can reap them (a
# leaked control loop would keep scaling a dead fleet under later tests)
_LIVE: "weakref.WeakSet" = weakref.WeakSet()


# ---- decisions --------------------------------------------------------------

HOLD = "hold"
OUT = "out"
IN = "in"


class ScaleDecision:
    """One policy verdict: `action` in {hold, out, in}, `delta` replicas,
    the `reason` that triggered it, and the evidence it was made on."""

    __slots__ = ("action", "delta", "reason", "evidence")

    def __init__(self, action: str, delta: int = 0, reason: str = "",
                 evidence: Optional[Dict[str, Any]] = None):
        self.action = action
        self.delta = int(delta)
        self.reason = reason
        self.evidence = dict(evidence or {})

    def __repr__(self):
        return (f"ScaleDecision({self.action}{self.delta:+d} "
                f"reason={self.reason})")


class ScalePolicy:
    """Pure scale-decision math. `decide()` consumes one fleet signal
    sample — worst shortest-window burn, queue fraction, actual/alive
    counts, pending front-door work — and returns a ScaleDecision.
    Stateful only in its clocks (calm-since, per-direction cooldowns);
    the injectable `now` makes traces deterministic."""

    def __init__(self, burn_high: Optional[float] = None,
                 burn_low: Optional[float] = None,
                 queue_high: Optional[float] = None,
                 queue_low: Optional[float] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 idle_after_s: Optional[float] = None,
                 zero_after_s: Optional[float] = None,
                 step: Optional[int] = None):
        def _f(v, flag):
            return float(_flags.flag(flag)) if v is None else float(v)

        self.burn_high = _f(burn_high, "autoscaler_burn_high")
        self.burn_low = _f(burn_low, "autoscaler_burn_low")
        self.queue_high = _f(queue_high, "autoscaler_queue_high")
        self.queue_low = _f(queue_low, "autoscaler_queue_low")
        self.min_replicas = int(_f(min_replicas,
                                   "autoscaler_min_replicas"))
        mx = int(_f(max_replicas, "autoscaler_max_replicas"))
        self.max_replicas = mx if mx > 0 else int(
            _flags.flag("fleet_max_replicas"))
        self.cooldown_s = _f(cooldown_s, "autoscaler_cooldown_s")
        self.idle_after_s = _f(idle_after_s, "autoscaler_idle_after_s")
        self.zero_after_s = _f(zero_after_s, "autoscaler_zero_after_s")
        self.step = max(1, int(_f(step, "autoscaler_step")))
        # clocks: None == never / not running
        self._calm_since: Optional[float] = None
        self._last_out: Optional[float] = None
        self._last_in: Optional[float] = None

    # -- clock helpers --
    def _cooled(self, last: Optional[float], now: float) -> bool:
        return last is None or now - last >= self.cooldown_s

    def _out(self, now: float, delta: int, reason: str,
             ev: Dict[str, Any]) -> ScaleDecision:
        self._last_out = now
        self._calm_since = None
        return ScaleDecision(OUT, delta, reason, ev)

    def _in(self, now: float, delta: int, reason: str,
            ev: Dict[str, Any]) -> ScaleDecision:
        self._last_in = now
        # the idle clock restarts: ONE scale-in per sustained-calm
        # window, the next needs a fresh window (plus the cooldown)
        self._calm_since = now
        return ScaleDecision(IN, delta, reason, ev)

    def decide(self, signal: Dict[str, Any],
               now: Optional[float] = None) -> ScaleDecision:
        """One verdict from one signal sample. `signal` keys: `burn`
        (worst per-source shortest-window burn), `queue_frac` (fleet
        queued work / aggregate capacity), `actual` (healthy replicas),
        `alive_sources` (telemetry sources feeding the burn signal),
        `pending` (front-door work with no replica to run it, optional)."""
        if now is None:
            now = time.monotonic()
        burn = float(signal.get("burn") or 0.0)
        queue = float(signal.get("queue_frac") or 0.0)
        actual = int(signal.get("actual") or 0)
        alive = int(signal.get("alive_sources") or 0)
        pending = int(signal.get("pending") or 0)
        ev = {"burn": burn, "queue_frac": queue, "actual": actual,
              "alive_sources": alive, "pending": pending}
        # 1. bootstrap / floor repair — not gated on a telemetry signal
        #    (a pool below its floor has nothing to report burn with)
        if actual < self.min_replicas:
            if not self._cooled(self._last_out, now):
                return ScaleDecision(HOLD, 0, "cooldown", ev)
            return self._out(now, self.min_replicas - actual,
                             "below_min", ev)
        # 2. scale-out from zero on front-door demand (a scaled-to-zero
        #    fleet has no replica sources to burn)
        if actual == 0 and pending > 0:
            if not self._cooled(self._last_out, now):
                return ScaleDecision(HOLD, 0, "cooldown", ev)
            return self._out(now, self.step, "cold_start", ev)
        # 3. blind — collector dead or nothing reporting: hold steady
        #    and freeze the idle clock (never scale in on missing data)
        if alive == 0 and actual > 0:
            self._calm_since = None
            return ScaleDecision(HOLD, 0, "no_signal", ev)
        hot = burn >= self.burn_high or queue >= self.queue_high
        calm = burn <= self.burn_low and queue <= self.queue_low
        if hot:
            self._calm_since = None
            if not self._cooled(self._last_out, now):
                return ScaleDecision(HOLD, 0, "cooldown", ev)
            if actual >= self.max_replicas:
                return ScaleDecision(HOLD, 0, "at_max", ev)
            delta = min(self.step, self.max_replicas - actual)
            reason = "burn_high" if burn >= self.burn_high \
                else "queue_high"
            return self._out(now, delta, reason, ev)
        if not calm:
            # the hysteresis band: neither threshold crossed — the idle
            # clock does not run here, so flapping near the low
            # thresholds cannot accumulate toward a scale-in
            self._calm_since = None
            return ScaleDecision(HOLD, 0, "steady", ev)
        if self._calm_since is None:
            self._calm_since = now
        idle_for = now - self._calm_since
        ev["idle_s"] = round(idle_for, 3)
        # surplus replicas drain one at a time at the idle threshold;
        # the LAST one (min_replicas=0 only) waits for the longer
        # zero_after_s — going dark costs a cold start on the next
        # request, so it takes more conviction
        if actual > max(self.min_replicas, 1):
            if idle_for >= self.idle_after_s \
                    and self._cooled(self._last_in, now):
                return self._in(now, 1, "sustained_idle", ev)
        elif actual == 1 and self.min_replicas == 0:
            if idle_for >= self.zero_after_s \
                    and self._cooled(self._last_in, now):
                return self._in(now, 1, "scale_to_zero", ev)
        return ScaleDecision(HOLD, 0, "calm", ev)


# ---- decision ledger --------------------------------------------------------

class DecisionLedger:
    """Bounded ring of scale actions with their triggering evidence —
    the audit trail the flight recorder dumps and `monitor top`
    renders. Sequence numbers make post-mortem ordering unambiguous."""

    def __init__(self, ring: Optional[int] = None):
        self._ring: deque = deque(maxlen=max(
            4, int(ring if ring is not None
                   else _flags.flag("autoscaler_ledger_ring"))))
        self._lock = _syncwatch.lock("autoscaler.DecisionLedger._lock")
        self._seq = 0
        self._counts: Dict[str, int] = {}

    def record(self, action: str, delta: int, reason: str,
               evidence: Dict[str, Any], outcome: str,
               target: int, actual: int) -> Dict[str, Any]:
        entry = {"seq": None, "ts": time.time(), "action": action,
                 "delta": int(delta), "reason": reason,
                 "evidence": dict(evidence), "outcome": outcome,
                 "target": target, "actual": actual}
        with self._lock:
            entry["seq"] = self._seq
            self._seq += 1
            self._ring.append(entry)
            self._counts[action] = self._counts.get(action, 0) + 1
        if _monitor._ENABLED:
            _monitor.count(f"autoscaler.decisions.{action}")
        return entry

    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._ring[-1]) if self._ring else None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"decisions": [dict(e) for e in self._ring],
                    "counts": dict(self._counts),
                    "recorded": self._seq}


# ---- actuator ---------------------------------------------------------------

class ReplicaPool:
    """The actuator: spawns/activates replicas through a caller-supplied
    `spawn()` (returning anything with a `replica_id` attribute and a
    `stop`/`kill`; an in-process `ReplicaAgent` or a subprocess wrapper
    both fit) and retires them through the router's graceful drain.
    Membership truth is the ROUTER's — `actual()` is its healthy count,
    so externally-joined replicas are scaled the same as spawned ones."""

    def __init__(self, router, spawn: Callable[[], Any],
                 spawn_timeout_s: Optional[float] = None):
        self.router = router
        self._spawn = spawn
        self._timeout = float(
            spawn_timeout_s if spawn_timeout_s is not None
            else _flags.flag("autoscaler_spawn_timeout_s"))
        self.handles: Dict[int, Any] = {}
        self.spawned = 0
        self.spawn_failures = 0
        self.drained = 0

    def actual(self) -> int:
        return len(self.router.healthy_replicas())

    # -- scale out --
    def scale_out(self, n: int = 1) -> Dict[str, Any]:
        """Spawn `n` replicas; each must answer its first 'PDHQ' within
        the spawn timeout or it is reaped (never routed to forever).
        Returns {"ok": [rids], "failed": int, "why": [reasons]}."""
        ok: List[int] = []
        why: List[str] = []
        for _ in range(max(1, int(n))):
            rid = self._spawn_one(why)
            if rid is not None:
                ok.append(rid)
        return {"ok": ok, "failed": len(why), "why": why}

    def _spawn_one(self, why: List[str]) -> Optional[int]:
        t0 = time.monotonic()
        try:
            handle = self._spawn()
        except Exception as e:
            self._spawn_failed(None, None, f"{type(e).__name__}: {e}",
                               why)
            return None
        rid = getattr(handle, "replica_id", None)
        while time.monotonic() - t0 < self._timeout:
            if rid is None:
                rid = getattr(handle, "replica_id", None)
            if rid is not None and any(
                    h.replica_id == rid
                    for h in self.router.healthy_replicas()):
                self.handles[int(rid)] = handle
                self.spawned += 1
                if _monitor._ENABLED:
                    _monitor.count("autoscaler.spawned")
                _obs.record_event("autoscaler.replica_spawned",
                                  replica=int(rid),
                                  took_s=round(time.monotonic() - t0, 3))
                return int(rid)
            poll = getattr(handle, "poll", None)
            if poll is not None and poll() is not None:
                break  # subprocess died before its first 'PDHQ' answer
            try:
                self.router.refresh()
            except Exception:
                pass  # store blip: the loop retries until the timeout
            time.sleep(min(0.05, self._timeout / 10.0))
        self._spawn_failed(handle, rid, "never_healthy", why)
        return None

    def _spawn_failed(self, handle, rid, reason: str,
                      why: List[str]) -> None:
        self.spawn_failures += 1
        why.append(reason)
        if _monitor._ENABLED:
            _monitor.count("autoscaler.spawn_failures")
        if handle is not None:
            _stop_handle(handle)
        if rid is not None:
            # reap the corpse: record + lease reclaimed so no router
            # probes it forever
            self.router.forget(int(rid))
        _obs.record_event("autoscaler.spawn_failed", replica=rid,
                          reason=reason)

    # -- scale in --
    def scale_in(self, n: int = 1) -> List[Dict[str, Any]]:
        """Drain the `n` least-loaded replicas gracefully ('PDDR': every
        accepted request completes or rejects) and reclaim their leases.
        A victim SIGKILLed mid-drain still converges: the connection
        error is recorded as `died_during_drain` and `forget()` reclaims
        its record + lease anyway."""
        results: List[Dict[str, Any]] = []
        for _ in range(max(1, int(n))):
            victims = self.router.healthy_replicas()
            if not victims:
                break
            victim = min(victims, key=lambda h: (
                float(h.stats.get("queue_depth", 0) or 0)
                + float(h.stats.get("inflight", 0) or 0)))
            rid = victim.replica_id
            outcome = "drained"
            try:
                self.router.drain(rid)
            except (ConnectionError, TimeoutError, OSError):
                outcome = "died_during_drain"
            except FleetError:
                outcome = "already_gone"
            except Exception:
                # a victim SIGKILLed mid-handshake can fail the drain
                # RPC with a protocol error rather than a clean
                # ConnectionError; the decision must still be recorded
                # and the lease still reclaimed or the pool wedges
                outcome = "drain_error"
            handle = self.handles.pop(rid, None)
            if handle is not None:
                _stop_handle(handle)
            self.router.forget(rid)
            self.drained += 1
            if _monitor._ENABLED:
                _monitor.count("autoscaler.drained")
            _obs.record_event("autoscaler.replica_drained", replica=rid,
                              outcome=outcome)
            results.append({"replica": rid, "outcome": outcome})
        return results

    def stop_all(self) -> None:
        """Teardown: stop every handle this pool spawned (no drain)."""
        handles, self.handles = dict(self.handles), {}
        for rid, handle in handles.items():
            _stop_handle(handle)
            try:
                self.router.forget(rid)
            except Exception:
                pass


def _stop_handle(handle) -> None:
    """Best-effort stop across handle shapes: ReplicaAgent.stop(drain=),
    a subprocess wrapper's kill(), or a bare stop()/close()."""
    for call in (lambda: handle.stop(drain=False),
                 lambda: handle.stop(),
                 lambda: handle.kill(),
                 lambda: handle.close()):
        try:
            call()
            return
        except TypeError:
            continue
        except AttributeError:
            continue
        except Exception:
            return  # it tried; a dead process raising is fine


# ---- the control loop -------------------------------------------------------

class Autoscaler:
    """The sense→decide→act loop. Each `FLAGS_autoscaler_interval_s`
    tick: read the fleet signal off the co-located TelemetryCollector
    (worst per-source shortest-window burn — NEVER the merged-gauge sum,
    which inflates with the source count — plus the aggregate queue
    fraction), ask the ScalePolicy for a verdict, actuate it through the
    ReplicaPool, record it in the DecisionLedger, and publish the pool
    doc back to the collector for `monitor top` + the `scale_blocked`
    alert. `tick()` is public so tests drive the loop deterministically."""

    def __init__(self, collector, pool: ReplicaPool,
                 policy: Optional[ScalePolicy] = None,
                 interval_s: Optional[float] = None,
                 queue_capacity: Optional[int] = None):
        self.collector = collector
        self.pool = pool
        self.policy = policy or ScalePolicy()
        self.ledger = DecisionLedger()
        self.interval_s = float(
            interval_s if interval_s is not None
            else _flags.flag("autoscaler_interval_s"))
        self._queue_capacity = max(1, int(
            queue_capacity if queue_capacity is not None
            else _flags.flag("serving_queue_depth")))
        self._spawn_retries = max(1, int(
            _flags.flag("autoscaler_spawn_retries")))
        self._spawn_budget = self._spawn_retries
        self._last_spawn_attempt: Optional[float] = None
        self._blocked_reason: Optional[str] = None
        self.target = pool.actual()
        self.ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        _LIVE.add(self)

    # -- lifecycle --
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self.target = max(self.target, self.pool.actual())
        self._thread = _syncwatch.Thread(
            target=self._run, name="autoscaler-loop", daemon=True)
        self._thread.start()
        return self

    def close(self, stop_pool: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
        if stop_pool:
            self.pool.stop_all()

    stop = close

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                continue  # a store/collector blip must not kill the loop

    # -- one loop iteration (public: tests drive it deterministically) --
    def tick(self, now: Optional[float] = None) -> ScaleDecision:
        if now is None:
            now = time.monotonic()
        signal = self._sense()
        decision = self.policy.decide(signal, now)
        self._act(decision, now)
        self._sweep_tenants(now)
        self._publish()
        self.ticks += 1
        return decision

    # -- sense --
    def _sense(self) -> Dict[str, Any]:
        burn = 0.0
        queued = 0.0
        alive = 0
        if self.collector is not None:
            for row in self.collector.fleet_table():
                if not row.get("alive") or row.get("role") != "replica":
                    continue
                alive += 1
                burn = max(burn, float(row.get("burn") or 0.0))
                queued += float(row.get("queue") or 0)
        frac = queued / (alive * self._queue_capacity) if alive else 0.0
        try:
            pending = int(self.pool.router.ledger.audit()["open"])
        except Exception:
            pending = 0
        return {"burn": burn, "queue_frac": frac,
                "actual": self.pool.actual(), "alive_sources": alive,
                "pending": pending}

    # -- act --
    def _act(self, decision: ScaleDecision, now: float) -> None:
        if decision.action == OUT:
            self._scale_out(decision, now)
        elif decision.action == IN:
            results = self.pool.scale_in(decision.delta)
            self.target = max(self.policy.min_replicas,
                              self.pool.actual())
            outcome = ",".join(r["outcome"] for r in results) or "no_victim"
            self.ledger.record(IN, -len(results), decision.reason,
                               decision.evidence, outcome,
                               self.target, self.pool.actual())

    def _scale_out(self, decision: ScaleDecision, now: float) -> None:
        # retry budget: consecutive spawn failures exhaust it and block
        # scale-out (the collector's scale_blocked alert fires); after a
        # cooldown one probe spawn is allowed — a recovered substrate
        # unblocks without operator action, a still-broken one re-arms
        if self._spawn_budget <= 0:
            cooled = (self._last_spawn_attempt is None
                      or now - self._last_spawn_attempt
                      >= self.policy.cooldown_s)
            if not cooled:
                self.ledger.record(OUT, 0, decision.reason,
                                   decision.evidence, "blocked",
                                   self.target, self.pool.actual())
                return
            self._spawn_budget = 1
        self._last_spawn_attempt = now
        self.target = min(self.policy.max_replicas,
                          max(self.target, self.pool.actual())
                          + decision.delta)
        res = self.pool.scale_out(decision.delta)
        if res["ok"]:
            self._spawn_budget = self._spawn_retries
            self._blocked_reason = None
            outcome = "spawned:" + ",".join(map(str, res["ok"]))
        else:
            self._spawn_budget -= res["failed"]
            if self._spawn_budget <= 0:
                self._spawn_budget = 0
                self._blocked_reason = (
                    "hbm_refused" if any("HBMBudget" in w
                                         for w in res["why"])
                    else "spawn_budget_exhausted")
                outcome = "blocked"
            else:
                outcome = "spawn_failed"
            self.target = self.pool.actual()
        self.ledger.record(
            OUT, len(res["ok"]), decision.reason,
            dict(decision.evidence, spawn_why=res["why"]), outcome,
            self.target, self.pool.actual())

    def _sweep_tenants(self, now: float) -> None:
        """Scale-to-zero for hosted tenants: one idle past the threshold
        with an empty queue is evicted through the replica's HBM-budget
        LRU path (model_ctl op 'evict'); a later host_model/rollout
        re-admits it, warm-started by the compile cache."""
        thr = float(_flags.flag("autoscaler_tenant_idle_s"))
        if thr < 0:
            return
        if thr == 0:
            thr = self.policy.zero_after_s
        for h in self.pool.router.healthy_replicas():
            tenants = h.stats.get("tenants") or {}
            for name, t in list(tenants.items()):
                if not isinstance(t, dict):
                    continue
                if float(t.get("idle_s") or 0.0) < thr \
                        or int(t.get("queue_depth") or 0) > 0:
                    continue
                try:
                    self.pool.router._model_ctl(h, "evict", name)
                except Exception:
                    continue  # busy/raced tenant: next sweep retries
                if _monitor._ENABLED:
                    _monitor.count("autoscaler.tenants_evicted")
                self.ledger.record(
                    "evict_tenant", 0, "tenant_idle",
                    {"model": name, "replica": h.replica_id,
                     "idle_s": t.get("idle_s")}, "evicted",
                    self.target, self.pool.actual())

    # -- publish / observability --
    def pool_doc(self) -> Dict[str, Any]:
        return {"target": self.target, "actual": self.pool.actual(),
                "blocked": self._blocked_reason is not None,
                "blocked_reason": self._blocked_reason,
                "spawn_failures": self.pool.spawn_failures,
                "last": self.ledger.last()}

    def _publish(self) -> None:
        c = self.collector
        if c is not None and hasattr(c, "pool_update"):
            try:
                c.pool_update(self.pool_doc())
            except Exception:
                pass  # a dying collector must not kill the control loop

    def snapshot(self) -> Dict[str, Any]:
        return {"target": self.target, "actual": self.pool.actual(),
                "ticks": self.ticks,
                "blocked_reason": self._blocked_reason,
                "spawn_budget": self._spawn_budget,
                "pool": {"spawned": self.pool.spawned,
                         "spawn_failures": self.pool.spawn_failures,
                         "drained": self.pool.drained},
                "policy": {"burn_high": self.policy.burn_high,
                           "burn_low": self.policy.burn_low,
                           "queue_high": self.policy.queue_high,
                           "queue_low": self.policy.queue_low,
                           "min": self.policy.min_replicas,
                           "max": self.policy.max_replicas,
                           "cooldown_s": self.policy.cooldown_s,
                           "idle_after_s": self.policy.idle_after_s,
                           "zero_after_s": self.policy.zero_after_s},
                "ledger": self.ledger.snapshot()}

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the decision ledger into a flight-recorder dump."""
        return _obs.dump(path, reason="autoscaler",
                         extra={"autoscaler": self.snapshot()})
