"""Fleet-of-replicas serving tier: a health-routed replica pool.

Reference parity: the cluster role of Paddle Serving (`Serving/python/
pipeline/` — DAG of op servers behind a gRPC gateway with channel-full
backpressure) re-based on this repo's own primitives instead of a
sidecar stack: replicas are `PredictorServer` processes wrapped in a
`ReplicaAgent`, membership is the C++ TCPStore + `ElasticManager` lease
plane (`parallel/elastic.py`), health is the `'PDHQ'` wire probe, and
routing is load-aware off each replica's own engine stats.

Topology — one `FleetRouter`, N `ReplicaAgent`s, one TCPStore:

    client -> FleetRouter.run()
                |  score replicas: queue_frac + w * slo_burn
                |  (stats from the 'PDHQ' probe, refreshed by the
                |   fleet-health thread every FLAGS_fleet_health_interval_s)
                v
              replica agent  -- PredictorServer -- ServingEngine(s)
                ^   heartbeats `lease:{id}` through ElasticManager;
                |   a missed lease OR a dispatch connection error marks
                |   the replica dead and its traffic re-routes within
                |   the ORIGINAL request deadline (failover loop)

Exactly-once: the router gives every request a sequence number in a
`SequenceLedger`; a failover retry re-dispatches the SAME sequence, and
the ledger refuses a second settle — a duplicate response (replica
answered but the connection died before the router saw it) is dropped
and counted (`fleet.duplicates_dropped`), never returned twice. The
chaos test audits the ledger: every sequence settles exactly once or is
accounted as abandoned/rejected.

Lifecycle verbs:
  - graceful drain ('PDDR'): every accepted request completes or is
    rejected overloaded — never silently dropped; the port closes.
  - versioned rollout: `FleetRouter.rollout()` pushes a new generation
    into the tenant's guard-checkpoint weight store, reloads ONE canary
    replica, watches the canary tenant's SLO burn over live probes, then
    promotes to the rest or instantly rolls back via the `.bak`
    generation (`guard.rollback_guard_state`).
  - multi-model hosting: `ReplicaAgent.host_model()` admits a
    `ModelTenant` (own engine + own `SloPlane`, so one tenant's burn
    cannot hide in another's average) under an explicit HBM budget —
    over-budget pushes evict idle tenants or fail with
    `HBMBudgetExceededError`, never over-subscribe.

Fault sites (chaos drills): `router.dispatch` (conn resets on the
dispatch path), `replica.register` (rendezvous failures),
`replica.drain` (drain-path faults).
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults as _faults
from .. import monitor as _monitor
from .. import obs as _obs
from ..core import flags as _flags
from ..guard import (guard_state_version, load_guard_state,
                     rollback_guard_state, save_guard_state)
from ..obs import slo as _slo
from ..parallel.elastic import ElasticManager
from .engine import EngineConfig, ServingEngine

__all__ = [
    "FleetRouter", "ReplicaAgent", "ModelTenant", "SequenceLedger",
    "RolloutResult", "FleetError", "NoHealthyReplicaError",
    "HBMBudgetExceededError", "render_fleet",
]

# unclosed routers/agents, so the test-suite leak fixture can both detect
# and reap them (a leaked health thread would poison every later test)
_LIVE: "weakref.WeakSet" = weakref.WeakSet()


class FleetError(RuntimeError):
    pass


class NoHealthyReplicaError(FleetError):
    """Every replica was dead, draining, or refused within the deadline."""


class HBMBudgetExceededError(FleetError):
    """Admitting the model would exceed the replica's HBM budget and no
    idle tenant could be evicted to make room."""


def _server_mod():
    # runtime import: inference/server.py imports paddle_tpu.serving at
    # module load, so a top-level import here would be circular
    from ..inference import server as _server
    return _server


# ---- exactly-once sequence ledger -------------------------------------------

class SequenceLedger:
    """Router-side exactly-once accounting. Every request gets one
    sequence number; failover re-dispatches the SAME sequence; the FIRST
    settle wins and any later one is refused (the caller drops the
    duplicate response). `audit()` is the chaos-test contract: sequences
    partition into settled / rejected / abandoned / open, and
    `duplicates` counts refused second settles."""

    def __init__(self):
        self._lock = _syncwatch.lock("fleet.SequenceLedger._lock")
        self._next = 0
        self._open: Dict[int, List[int]] = {}      # seq -> replicas tried
        self._settled: Dict[int, int] = {}         # seq -> replica that won
        self._rejected: Dict[int, str] = {}        # seq -> terminal status
        self._duplicates = 0

    def next_seq(self) -> int:
        with self._lock:
            seq = self._next
            self._next += 1
            self._open[seq] = []
            return seq

    def dispatch(self, seq: int, replica_id: int) -> None:
        with self._lock:
            self._open.setdefault(seq, []).append(replica_id)

    def settle(self, seq: int, replica_id: int) -> bool:
        """First settle returns True; a later one is a DUPLICATE: refused,
        counted, and the caller must drop the response."""
        # the monitor count stays OUTSIDE the critical section: it takes
        # the registry lock, and nesting that under the ledger lock puts
        # a foreign lock inside the request hot path (syncwatch dogfood)
        with self._lock:
            if seq in self._settled:
                self._duplicates += 1
                dup = True
            else:
                self._settled[seq] = replica_id
                self._open.pop(seq, None)
                dup = False
        if dup and _monitor._ENABLED:
            _monitor.count("fleet.duplicates_dropped")
        return not dup

    def reject(self, seq: int, why: str) -> None:
        """Terminal non-answer (deadline, no healthy replica): the caller
        surfaced an error for this sequence — it is accounted, not lost."""
        with self._lock:
            if seq not in self._settled:
                self._rejected[seq] = why
                self._open.pop(seq, None)

    def audit(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "issued": self._next,
                "settled": len(self._settled),
                "rejected": len(self._rejected),
                "open": len(self._open),
                "duplicates": self._duplicates,
                "lost": self._next - len(self._settled)
                - len(self._rejected) - len(self._open),
            }


# ---- model tenancy ----------------------------------------------------------

class ModelTenant:
    """One hosted model on a replica: a guard-checkpoint versioned weight
    store, a handler factory, its OWN ServingEngine (queue isolation) and
    its OWN SloPlane (per-tenant error budget — one tenant's burn must
    not hide in the replica average).

    `handler_factory(arrays, meta) -> callable` builds the predictor
    callable from a weight generation; `reload()` re-reads the NEWEST
    committed generation and swaps the handler in place (the engine and
    its warmed buckets survive a version swap)."""

    def __init__(self, name: str, dirname: str,
                 handler_factory: Callable[[Dict[str, np.ndarray], dict],
                                           Callable],
                 engine_config: Optional[EngineConfig] = None,
                 slo: Optional[_slo.SloPlane] = None,
                 bytes_hint: Optional[int] = None):
        self.name = name
        self.dirname = dirname
        self.handler_factory = handler_factory
        self._handler: Optional[Callable] = None
        self._lock = _syncwatch.lock("fleet.ModelTenant._lock")
        self.version = 0
        self.bytes = 0
        self._bytes_hint = bytes_hint
        self.last_used = time.monotonic()
        self.slo = slo
        # a stable closure: reload() swaps self._handler, the engine keeps
        # the same callable (and its compiled buckets)
        tenant = self

        def _call(*arrays):
            tenant.last_used = time.monotonic()
            h = tenant._handler
            if h is None:
                raise FleetError(f"model {tenant.name!r} has no loaded "
                                 "generation")
            return h(*arrays)

        self.engine = ServingEngine(_call, engine_config)
        if slo is not None:
            self.engine.slo_plane = slo

    def reload(self) -> int:
        """Load the newest committed weight generation; returns its
        version. Raises (and keeps the PREVIOUS handler serving) when the
        store has no intact generation."""
        arrays, meta = load_guard_state(self.dirname)
        with self._lock:
            self._handler = self.handler_factory(arrays, meta)
            self.version = guard_state_version(self.dirname)
            self.bytes = self._bytes_hint if self._bytes_hint is not None \
                else sum(int(np.asarray(a).nbytes) for a in arrays.values())
        if _monitor._ENABLED:
            _monitor.gauge_set(f"mem.model.{self.name}.bytes", self.bytes)
        return self.version

    def stats(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "bytes": self.bytes,
            "slo": self.slo.stats() if self.slo is not None else None,
            "queue_depth": self.engine.stats()["queue_depth"],
            "idle_s": round(time.monotonic() - self.last_used, 3),
        }


# ---- replica side -----------------------------------------------------------

class ReplicaAgent:
    """One fleet member: wraps a `PredictorServer`, registers with the
    fleet's TCPStore, heartbeats through `ElasticManager`, answers the
    fleet control verbs (drain, model reload/rollback), and hosts extra
    models under an explicit HBM budget."""

    def __init__(self, predictor, store, fleet: str = "fleet",
                 host: str = "127.0.0.1", port: int = 0,
                 engine_config: Optional[EngineConfig] = None,
                 replica_id: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 slo: Optional[_slo.SloPlane] = None):
        self.store = store
        self.fleet = fleet
        self.replica_id = replica_id
        budget_mb = float(_flags.flag("fleet_hbm_budget_mb"))
        self.hbm_budget_bytes = hbm_budget_bytes if hbm_budget_bytes \
            is not None else int(budget_mb * (1 << 20))
        self.tenants: Dict[str, ModelTenant] = {}
        self._elastic: Optional[ElasticManager] = None
        self._exporter = None   # TelemetryExporter under FLAGS_telemetry
        self._closed = False
        srv = _server_mod()
        self.server = srv.PredictorServer(
            predictor, host=host, port=port, engine_config=engine_config,
            on_drain=self._on_drain, on_model_ctl=self._on_model_ctl,
            stats_extra=self._stats_extra)
        if slo is not None:
            self.server.engine.slo_plane = slo
        self.slo = slo
        _LIVE.add(self)

    # -- store keys --
    def _key(self, suffix: str) -> str:
        return f"fleet:{self.fleet}:{suffix}"

    # -- lifecycle --
    def start(self) -> "ReplicaAgent":
        if _faults._ENABLED:
            _faults.check("replica.register")
        if self.replica_id is None:
            # rendezvous: claim the next id (native add-counters are
            # atomic across processes)
            self.replica_id = int(
                self.store.add(self._key("next_id"), 1)) - 1
        max_replicas = int(_flags.flag("fleet_max_replicas"))
        if self.replica_id >= max_replicas:
            raise FleetError(
                f"replica id {self.replica_id} >= FLAGS_fleet_max_replicas="
                f"{max_replicas}")
        self.server.start()
        self.server.drain_info = {"replica_id": self.replica_id}
        record = {"host": self.server.host, "port": self.server.port,
                  "pid": os.getpid(), "ts": time.time()}
        self.store.set(self._key(f"replica:{self.replica_id}"),
                       json.dumps(record))
        self._elastic = ElasticManager(
            _PrefixStore(self.store, self._key("")), rank=self.replica_id,
            world_size=max_replicas,
            lease_ttl=float(_flags.flag("fleet_lease_ttl_s")),
            heartbeat_interval=float(_flags.flag("fleet_heartbeat_s")))
        self._elastic.register()
        if _flags.flag("telemetry"):
            from ..obs import telemetry as _telemetry
            self._exporter = _telemetry.TelemetryExporter(
                self.store, source=f"replica-{self.replica_id}",
                role="replica", fleet=self.fleet,
                meta={"replica_id": self.replica_id}).start()
        _obs.record_event("fleet.replica_register",
                          replica=self.replica_id, port=self.server.port)
        return self

    def _deregister(self) -> None:
        if self._elastic is not None:
            self._elastic.stop()
            self._elastic = None
        if self.replica_id is not None:
            try:  # the store has no delete: empty value == deregistered
                self.store.set(self._key(f"replica:{self.replica_id}"), b"")
                self.store.set(self._key(f"lease:{self.replica_id}"), b"")
            except Exception:
                pass  # store may already be gone on teardown

    def _on_drain(self) -> None:
        # runs between the port closing and the engines draining: stop
        # advertising FIRST so the router routes around us while queued
        # work completes
        if _faults._ENABLED:
            _faults.check("replica.drain")
        self._deregister()
        if self._exporter is not None:
            # push-fed fast path: the router learns of the drain from the
            # collector relay, not the next poll sweep
            self._exporter.event("drain", replica_id=self.replica_id)
        _obs.record_event("fleet.replica_drain", replica=self.replica_id)

    def drain(self) -> dict:
        report = self.server.drain()
        report["replica_id"] = self.replica_id
        return report

    def stop(self, drain: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if drain:
            self.drain()
        else:
            self._deregister()
            self.server.stop(drain=False)
        if self._exporter is not None:
            self._exporter.stop()   # final flush ships the drain event
            self._exporter = None
        for t in self.tenants.values():
            t.engine.stop(drain=drain)

    close = stop

    # -- multi-model hosting under an HBM budget --
    def host_model(self, tenant: ModelTenant) -> ModelTenant:
        """Admit a tenant: load its newest generation, then check the
        budget — evicting IDLE tenants (no queued work, least recently
        used first) if needed; refuse with `HBMBudgetExceededError` when
        the model cannot fit even after evictions."""
        tenant.reload()
        if self.hbm_budget_bytes > 0:
            need = tenant.bytes
            used = sum(t.bytes for t in self.tenants.values())
            if used + need > self.hbm_budget_bytes:
                # plan the evictions FIRST (idle tenants, least recently
                # used): a doomed admission must refuse without having
                # torn anything down
                plan: List[str] = []
                would_free = 0
                for name, cand in sorted(
                        self.tenants.items(),
                        key=lambda kv: kv[1].last_used):
                    if used - would_free + need <= self.hbm_budget_bytes:
                        break
                    if cand.engine.stats()["queue_depth"] > 0:
                        continue  # busy tenants are not evictable
                    plan.append(name)
                    would_free += cand.bytes
                if used - would_free + need > self.hbm_budget_bytes:
                    raise HBMBudgetExceededError(
                        f"model {tenant.name!r} needs {need}B; "
                        f"{used}B of {self.hbm_budget_bytes}B in use and "
                        "no idle tenant to evict")
                for name in plan:
                    self.evict_model(name)
        self.tenants[tenant.name] = tenant
        self.server.register_model(tenant.name, tenant.engine)
        if _monitor._ENABLED:
            _monitor.count("fleet.models_hosted")
        _obs.record_event("fleet.model_hosted", replica=self.replica_id,
                          model=tenant.name, bytes=tenant.bytes,
                          version=tenant.version)
        return tenant

    def evict_model(self, name: str) -> None:
        tenant = self.tenants.pop(name, None)
        if tenant is None:
            return
        self.server.unregister_model(name, drain=True)
        if _monitor._ENABLED:
            _monitor.count("fleet.models_evicted")
            _monitor.gauge_set(f"mem.model.{name}.bytes", 0)
        _obs.record_event("fleet.model_evicted", replica=self.replica_id,
                          model=name)

    # -- control-plane hooks wired into PredictorServer --
    def _on_model_ctl(self, req: dict) -> dict:
        op = req.get("op")
        name = req.get("model", "")
        tenant = self.tenants.get(name)
        if tenant is None:
            raise FleetError(f"unknown model {name!r}")
        if op == "reload":
            version = tenant.reload()
        elif op == "rollback":
            version = rollback_guard_state(tenant.dirname)
            tenant.reload()
            if _monitor._ENABLED:
                _monitor.count("fleet.model_rollbacks")
        elif op == "evict":
            # scale-to-zero for an idle tenant: the autoscaler rides the
            # same LRU eviction path the HBM budget uses, so a later
            # host_model() re-admits the tenant cold (weights reload from
            # the guard store, the compile cache warm-starts the engine)
            version = tenant.version
            if tenant.engine.stats()["queue_depth"] > 0:
                raise FleetError(f"model {name!r} is busy; not evictable")
            self.evict_model(name)
        else:
            raise FleetError(f"unknown model-ctl op {op!r}")
        _obs.record_event("fleet.model_ctl", replica=self.replica_id,
                          model=name, op=op, version=version)
        return {"ok": True, "model": name, "op": op, "version": version}

    def _stats_extra(self) -> dict:
        extra: Dict[str, Any] = {"replica_id": self.replica_id}
        if self.tenants:
            extra["tenants"] = {n: t.stats()
                                for n, t in self.tenants.items()}
        if self.hbm_budget_bytes > 0:
            extra["hbm"] = {
                "budget_bytes": self.hbm_budget_bytes,
                "used_bytes": sum(t.bytes
                                  for t in self.tenants.values()),
            }
        return extra

    @property
    def host(self):
        return self.server.host

    @property
    def port(self):
        return self.server.port


# promoted to parallel/elastic.py (the PS HA plane shares it); the
# underscore alias keeps this module's call sites and pickles stable
from ..parallel.elastic import PrefixStore as _PrefixStore  # noqa: E402
from ..utils import syncwatch as _syncwatch


# ---- router side ------------------------------------------------------------

class _ReplicaHandle:
    """Router-side view of one replica: its record, freshest probe stats,
    health verdict, and a small pool of persistent connections."""

    def __init__(self, replica_id: int, host: str, port: int):
        self.replica_id = replica_id
        self.host = host
        self.port = port
        self.healthy = True
        self.draining = False
        self.stats: Dict[str, Any] = {}
        self.served = 0
        self.failures = 0
        self.died_at: Optional[float] = None
        self.detected_dead_at: Optional[float] = None
        self._pool: List[Any] = []
        self._pool_lock = _syncwatch.lock("fleet._ReplicaHandle._pool_lock")

    def acquire(self, connect_timeout: float):
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        srv = _server_mod()
        return srv.PredictorClient(
            self.host, self.port, failover=False, max_retries=0,
            connect_timeout=connect_timeout)

    def release(self, client) -> None:
        with self._pool_lock:
            if len(self._pool) < 8:
                self._pool.append(client)
                return
        client.close()

    def close_pool(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for c in pool:
            c.close()

    def mark_dead(self) -> None:
        self.healthy = False
        if self.detected_dead_at is None:
            self.detected_dead_at = time.monotonic()
        self.close_pool()

    def score(self, burn_weight: float) -> float:
        """Load score, lower routes first: queue fraction + weighted SLO
        burn (shortest window) off the last 'PDHQ' probe."""
        s = self.stats
        cap = max(1, int(s.get("queue_capacity", 1) or 1))
        q = (float(s.get("queue_depth", 0)) +
             float(s.get("inflight", 0))) / cap
        return q + burn_weight * _slo.shortest_window_burn(s.get("slo"))


class RolloutResult:
    def __init__(self, model: str, version: int, canary: int,
                 promoted: bool, rolled_back: bool, canary_burn: float,
                 probed: int):
        self.model = model
        self.version = version
        self.canary = canary
        self.promoted = promoted
        self.rolled_back = rolled_back
        self.canary_burn = canary_burn
        self.probed = probed

    def __repr__(self):
        verdict = "promoted" if self.promoted else (
            "rolled_back" if self.rolled_back else "undecided")
        return (f"RolloutResult({self.model}@v{self.version} "
                f"canary={self.canary} {verdict} "
                f"burn={self.canary_burn:.3f})")


class FleetRouter:
    """Load-aware front-end over the replica pool. Discovers replicas
    from the fleet's TCPStore records, probes them on the fleet-health
    thread, scores each by `queue_frac + FLAGS_fleet_route_burn_weight *
    slo_burn`, and dispatches with exactly-once failover (see module
    docstring)."""

    def __init__(self, store, fleet: str = "fleet",
                 slo: Optional[_slo.SloPlane] = None):
        self.store = store
        self.fleet = fleet
        self.replicas: Dict[int, _ReplicaHandle] = {}
        self.ledger = SequenceLedger()
        self.slo = slo
        self._lock = _syncwatch.lock("fleet.FleetRouter._lock")
        self._stop = threading.Event()
        self._burn_weight = float(_flags.flag("fleet_route_burn_weight"))
        self._connect_timeout = float(
            _flags.flag("serving_client_connect_timeout_s"))
        self._health_interval = float(
            _flags.flag("fleet_health_interval_s"))
        self._max_replicas = int(_flags.flag("fleet_max_replicas"))
        lease_ttl = float(_flags.flag("fleet_lease_ttl_s"))
        # a handle dead this long with NO live lease is a corpse: reaped
        # from membership (and its stale record cleared) instead of being
        # probed forever — long enough that a live-but-slow replica's
        # lease always outruns it
        self._reap_after = max(2.0 * lease_ttl,
                               4.0 * self._health_interval)
        # prompt death detection: the elastic watcher fires on a missed
        # lease without waiting for the next health sweep
        self._elastic = ElasticManager(
            _PrefixStore(store, f"fleet:{self.fleet}:"), rank=-1,
            world_size=self._max_replicas,
            lease_ttl=lease_ttl,
            heartbeat_interval=float(_flags.flag("fleet_heartbeat_s")))
        self._health_thread: Optional[threading.Thread] = None
        self._closed = False
        _LIVE.add(self)

    def start(self) -> "FleetRouter":
        self.refresh()
        self._elastic.on_rank_dead(
            self._on_rank_dead,
            interval=min(self._health_interval,
                         self._elastic.heartbeat_interval))
        self._health_thread = _syncwatch.Thread(
            target=self._health_loop, daemon=True, name="fleet-health")
        self._health_thread.start()
        return self

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._elastic.stop()
        if self._health_thread is not None:
            self._health_thread.join(timeout=2)
            self._health_thread = None
        with self._lock:
            handles = list(self.replicas.values())
        for h in handles:
            h.close_pool()

    stop = close

    # -- membership + health --
    def _on_rank_dead(self, rank: int) -> None:
        with self._lock:
            h = self.replicas.get(rank)
        if h is not None and h.healthy:
            h.mark_dead()
            if _monitor._ENABLED:
                _monitor.count("fleet.replicas_lost")
            _obs.record_event("fleet.replica_dead", replica=rank,
                              via="lease")
            from ..obs import telemetry as _telemetry
            _telemetry.emit("lease_expiry", replica_id=rank)

    # -- telemetry fast path --
    def attach_telemetry(self, collector) -> "FleetRouter":
        """Subscribe to a TelemetryCollector's event relay: a pushed
        death/drain marks the replica dead the moment the collector's
        connection reader sees EOF (<1s after a SIGKILL), instead of
        waiting out the lease TTL or the next 'PDHQ' poll sweep — both
        of which keep running as fallback."""
        collector.subscribe(self._on_telemetry_event)
        return self

    def _on_telemetry_event(self, ev: Dict[str, Any]) -> None:
        kind = ev.get("kind")
        if kind not in ("death", "drain"):
            return
        detail = ev.get("detail") or {}
        rid = detail.get("replica_id")
        if rid is None:
            return
        with self._lock:
            h = self.replicas.get(int(rid))
        if h is None:
            return
        if kind == "drain":
            if not h.draining:
                h.draining = True
                h.close_pool()
            return
        if h.healthy:
            h.mark_dead()
            if _monitor._ENABLED:
                _monitor.count("fleet.replicas_lost")
            _obs.record_event("fleet.replica_dead", replica=int(rid),
                              via="telemetry")

    def forget(self, replica_id: int, reclaim: bool = True) -> bool:
        """Remove a replica from membership entirely. With `reclaim`,
        also clear its store record and lease (the store has no delete;
        empty == gone) so neither this router nor any other ever probes
        the corpse again. The autoscaler's pool calls this for a spawn
        that never answered its first 'PDHQ'; `refresh` calls it for any
        handle dead past the reap window with no live lease."""
        with self._lock:
            h = self.replicas.pop(replica_id, None)
        if h is not None:
            h.close_pool()
        if reclaim:
            try:
                self.store.set(
                    f"fleet:{self.fleet}:replica:{replica_id}", b"")
                self._elastic.reclaim(replica_id)
            except Exception:
                pass  # store gone on teardown: nothing left to reclaim
        if h is not None:
            _obs.record_event("fleet.replica_reaped", replica=replica_id)
        return h is not None

    def _reap_if_corpse(self, h: _ReplicaHandle) -> bool:
        """A handle that has been dead past the reap window AND holds no
        live lease is a corpse — a replica that died between spawn and
        its first 'PDHQ' answer would otherwise keep a stale record that
        every sweep probes forever."""
        if h.healthy or h.detected_dead_at is None:
            return False
        if time.monotonic() - h.detected_dead_at < self._reap_after:
            return False
        try:
            alive = set(self._elastic.alive_ranks())
        except Exception:
            return False  # store blip: reap on a later sweep
        if h.replica_id in alive:
            return False
        self.forget(h.replica_id)
        if _monitor._ENABLED:
            _monitor.count("fleet.replicas_reaped")
        return True

    def refresh(self) -> None:
        """One membership + health sweep (the fleet-health thread calls
        this every FLAGS_fleet_health_interval_s; tests call it directly
        for determinism)."""
        for rid in range(self._max_replicas):
            try:
                raw = self.store.get(f"fleet:{self.fleet}:replica:{rid}")
            except KeyError:
                continue
            if not raw:  # empty record == deregistered (drained)
                with self._lock:
                    h = self.replicas.get(rid)
                if h is not None and not h.draining:
                    h.draining = True
                    h.close_pool()
                continue
            try:
                rec = json.loads(raw.decode())
            except ValueError:
                continue
            joined = False
            with self._lock:
                h = self.replicas.get(rid)
                rejoin = (h is not None
                          and (h.host, h.port) != (rec["host"],
                                                   rec["port"]))
                if h is None or rejoin:
                    h = _ReplicaHandle(rid, rec["host"], rec["port"])
                    self.replicas[rid] = h
                    joined = True
            # counter + event ride OUTSIDE the membership lock: both take
            # foreign (monitor/obs ring) locks of their own, and nothing
            # here needs the membership view (syncwatch dogfood)
            if joined:
                if _monitor._ENABLED:
                    _monitor.count("fleet.replicas_joined")
                _obs.record_event("fleet.replica_joined", replica=rid,
                                  port=rec["port"], rejoin=rejoin)
            self._probe(h)
            self._reap_if_corpse(h)

    def _probe(self, h: _ReplicaHandle) -> None:
        try:
            client = h.acquire(self._connect_timeout)
        except Exception:
            h.mark_dead()
            return
        try:
            h.stats = client.health(deadline_ms=max(
                1000.0, self._health_interval * 2000.0))
        except Exception:
            client.close()
            h.mark_dead()
            return
        h.release(client)
        h.draining = bool(h.stats.get("draining"))
        if not h.healthy:
            h.detected_dead_at = None
            h.died_at = None
            _obs.record_event("fleet.replica_recovered",
                              replica=h.replica_id)
        h.healthy = True

    def _health_loop(self) -> None:
        while not self._stop.wait(self._health_interval):
            try:
                self.refresh()
            except Exception:
                continue  # a store blip must not kill the health plane

    def healthy_replicas(self) -> List[_ReplicaHandle]:
        with self._lock:
            hs = list(self.replicas.values())
        return [h for h in hs if h.healthy and not h.draining]

    # -- dispatch --
    def _pick(self, exclude) -> Optional[_ReplicaHandle]:
        best, best_score = None, None
        for h in self.healthy_replicas():
            if h.replica_id in exclude:
                continue
            s = h.score(self._burn_weight)
            if best_score is None or s < best_score:
                best, best_score = h, s
        return best

    def run(self, arrays: Sequence[np.ndarray],
            deadline_ms: Optional[float] = None,
            model: Optional[str] = None) -> Tuple[int, Any]:
        """Route one request. Returns (wire_status, payload) like
        `PredictorClient.run`. A replica that dies mid-request fails over
        to the next-best replica within the ORIGINAL deadline; overload
        answers also fail over (another replica may have room). A
        momentarily all-dead pool is ridden out within the deadline
        (refresh + short waits) rather than failed fast. Raises
        `NoHealthyReplicaError` when the pool is exhausted and
        `TimeoutError` when the deadline expires first."""
        seq = self.ledger.next_seq()
        deadline = (time.monotonic() + deadline_ms / 1000.0
                    if deadline_ms is not None else None)
        attempts = max(1, int(_flags.flag("fleet_failover_attempts")))
        tried: set = set()
        dispatches = 0
        t0 = time.monotonic()
        last_err: Optional[Exception] = None
        overloaded: Optional[Tuple[int, Any]] = None
        while dispatches < attempts:
            if deadline is not None and time.monotonic() >= deadline:
                break
            h = self._pick(tried)
            if h is None and tried:
                # every UNTRIED replica is out; a failover retry may
                # revisit a tried one (the ledger still dedups, and a
                # reset victim is often healthy again by now)
                tried = set()
                h = self._pick(tried)
            if h is None:
                # transient all-dead blip (a burst of resets can mark
                # replicas dead faster than the health loop revives
                # them): refresh membership and ride it out WITHIN the
                # deadline instead of failing fast
                try:
                    self.refresh()
                except Exception:
                    pass
                h = self._pick(tried)
                if h is None:
                    if deadline is None:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    time.sleep(min(0.05, remaining))
                    continue
            tried.add(h.replica_id)
            dispatches += 1
            self.ledger.dispatch(seq, h.replica_id)
            remaining_ms = None
            if deadline is not None:
                remaining_ms = max(1.0,
                                   (deadline - time.monotonic()) * 1e3)
            try:
                if _faults._ENABLED:
                    _faults.check("router.dispatch")
                client = h.acquire(self._connect_timeout)
                try:
                    status, payload = client.run(
                        arrays, deadline_ms=remaining_ms, model=model)
                except BaseException:
                    client.close()
                    raise
                h.release(client)
            except (ConnectionError, TimeoutError, OSError) as e:
                last_err = e
                h.mark_dead()
                h.failures += 1
                if _monitor._ENABLED:
                    _monitor.count("fleet.failovers")
                _obs.record_event("fleet.failover", replica=h.replica_id,
                                  seq=seq, error=type(e).__name__)
                continue
            srv = _server_mod()
            if status == srv.STATUS_OVERLOADED:
                # healthy backpressure: remember it, try a replica with
                # room (the pool may absorb what one member shed)
                overloaded = (status, payload)
                h.failures += 1
                continue
            if not self.ledger.settle(seq, h.replica_id):
                # a failover retry already answered this sequence: this
                # response is the duplicate — drop it
                continue
            h.served += 1
            self._slo_record(t0, status)
            return status, payload
        # terminal: no answer within the budget
        if overloaded is not None:
            self.ledger.reject(seq, "overloaded")
            self._slo_record(t0, _server_mod().STATUS_OVERLOADED)
            return overloaded
        if deadline is not None and time.monotonic() >= deadline:
            self.ledger.reject(seq, "deadline")
            self._slo_record(t0, _server_mod().STATUS_DEADLINE)
            raise TimeoutError(
                f"fleet deadline exceeded after {len(tried)} attempts"
            ) from last_err
        self.ledger.reject(seq, "no_healthy_replica")
        self._slo_record(t0, _server_mod().STATUS_ERROR)
        raise NoHealthyReplicaError(
            f"no healthy replica (tried {sorted(tried)})") from last_err

    def _slo_record(self, t0: float, status: int) -> None:
        p = self.slo
        if p is None:
            return
        srv = _server_mod()
        outcome = {srv.STATUS_OK: _slo.OUTCOME_OK,
                   srv.STATUS_OVERLOADED: _slo.OUTCOME_REJECTED,
                   srv.STATUS_DEADLINE: _slo.OUTCOME_DEADLINE}.get(
                       status, _slo.OUTCOME_ERROR)
        p.record(time.monotonic() - t0, outcome)

    # -- lifecycle verbs --
    def drain(self, replica_id: int) -> dict:
        """Gracefully drain one replica ('PDDR'): its accepted work
        completes, its port closes, its lease deregisters; the health
        plane routes around it immediately."""
        with self._lock:
            h = self.replicas.get(replica_id)
        if h is None:
            raise FleetError(f"unknown replica {replica_id}")
        srv = _server_mod()
        client = srv.PredictorClient(h.host, h.port, failover=False,
                                     connect_timeout=self._connect_timeout)
        try:
            report = client.drain()
        finally:
            client.close()
        h.draining = True
        h.healthy = False
        h.close_pool()
        if _monitor._ENABLED:
            _monitor.count("fleet.drains")
        _obs.record_event("fleet.replica_drained", replica=replica_id)
        return report

    def _model_ctl(self, h: _ReplicaHandle, op: str, model: str) -> dict:
        srv = _server_mod()
        client = srv.PredictorClient(h.host, h.port, failover=False,
                                     connect_timeout=self._connect_timeout)
        try:
            return client.model_ctl(op, model)
        finally:
            client.close()

    def rollout(self, model: str, dirname: str,
                arrays: Dict[str, np.ndarray], meta: dict,
                probes: Sequence[Sequence[np.ndarray]],
                canary: Optional[int] = None,
                probe_deadline_ms: float = 2000.0) -> RolloutResult:
        """Versioned canary rollout. Commits the new generation into the
        tenant's shared weight store, reloads ONE canary replica, drives
        the probe requests at the canary's tenant, reads the canary's
        per-tenant SLO burn off a fresh 'PDHQ' probe, then either
        promotes (reload everywhere else) or instantly rolls back via the
        guard `.bak` generation. The aggregate error budget stays
        bounded: only the canary ever served the bad version."""
        candidates = self.healthy_replicas()
        if not candidates:
            raise NoHealthyReplicaError("no replica to canary on")
        if canary is None:
            canary_h = candidates[0]
        else:
            canary_h = next((h for h in candidates
                             if h.replica_id == canary), None)
            if canary_h is None:
                raise FleetError(f"canary replica {canary} not healthy")
        save_guard_state(dirname, arrays, meta)
        ctl = self._model_ctl(canary_h, "reload", model)
        version = int(ctl.get("version", 0))
        _obs.record_event("fleet.rollout_canary", model=model,
                          version=version, canary=canary_h.replica_id)
        # drive the probes at the CANARY specifically (routing would
        # spread them and dilute the signal)
        srv = _server_mod()
        client = srv.PredictorClient(canary_h.host, canary_h.port,
                                     failover=False,
                                     connect_timeout=self._connect_timeout)
        probed = 0
        try:
            for p in probes:
                try:
                    client.run(list(p), deadline_ms=probe_deadline_ms,
                               model=model)
                except (ConnectionError, TimeoutError, OSError):
                    pass  # the burn accounting below is the verdict
                probed += 1
            stats = client.health(deadline_ms=probe_deadline_ms)
        finally:
            client.close()
        tenant = (stats.get("tenants") or {}).get(model) or {}
        burn = _slo.shortest_window_burn(tenant.get("slo"))
        threshold = float(_flags.flag("fleet_canary_burn"))
        if burn > threshold:
            self._model_ctl(canary_h, "rollback", model)
            if _monitor._ENABLED:
                _monitor.count("fleet.rollbacks")
            _obs.record_event("fleet.rollout_rollback", model=model,
                              version=version, burn=burn)
            from ..obs import telemetry as _telemetry
            _telemetry.emit("rollout", model=model, version=version,
                            burn=burn, promoted=False)
            return RolloutResult(model, version, canary_h.replica_id,
                                 promoted=False, rolled_back=True,
                                 canary_burn=burn, probed=probed)
        for h in self.healthy_replicas():
            if h.replica_id == canary_h.replica_id:
                continue
            try:
                self._model_ctl(h, "reload", model)
            except (ConnectionError, TimeoutError, OSError):
                h.mark_dead()
        if _monitor._ENABLED:
            _monitor.count("fleet.promotions")
        _obs.record_event("fleet.rollout_promote", model=model,
                          version=version, burn=burn)
        from ..obs import telemetry as _telemetry
        _telemetry.emit("rollout", model=model, version=version,
                        burn=burn, promoted=True)
        return RolloutResult(model, version, canary_h.replica_id,
                             promoted=True, rolled_back=False,
                             canary_burn=burn, probed=probed)

    def rollback_model(self, model: str) -> Dict[int, int]:
        """Instant fleet-wide rollback of `model` to its guard `.bak`
        generation ('PDMV' model-ctl on every healthy replica). Returns
        replica id -> restored version. The online rollback guard drives
        this when a poisoned table generation reaches serving."""
        restored: Dict[int, int] = {}
        for h in self.healthy_replicas():
            try:
                ctl = self._model_ctl(h, "rollback", model)
                restored[h.replica_id] = int(ctl.get("version", 0))
            except (ConnectionError, TimeoutError, OSError):
                h.mark_dead()
        if _monitor._ENABLED:
            _monitor.count("fleet.rollbacks")
        _obs.record_event("fleet.model_rollback", model=model,
                          replicas=sorted(restored))
        from ..obs import telemetry as _telemetry
        _telemetry.emit("model_rollback", model=model,
                        replicas=sorted(restored))
        return restored

    # -- observability --
    def snapshot(self) -> Dict[str, Any]:
        """The `fleet` section of an obs dump / the monitor CLI table."""
        with self._lock:
            hs = list(self.replicas.items())
        out: Dict[str, Any] = {"fleet": self.fleet, "replicas": {}}
        for rid, h in hs:
            s = h.stats
            out["replicas"][str(rid)] = {
                "host": h.host, "port": h.port,
                "healthy": h.healthy, "draining": h.draining,
                "score": round(h.score(self._burn_weight), 4),
                "served": h.served, "failures": h.failures,
                "queue_depth": s.get("queue_depth", 0),
                "warm_start_ms": s.get("warm_start_ms"),
                "tenants": sorted((s.get("tenants") or {}).keys()),
            }
        out["ledger"] = self.ledger.audit()
        if self.slo is not None:
            out["slo"] = self.slo.stats()
        return out

    def dump(self, path: Optional[str] = None,
             reason: str = "fleet") -> Optional[str]:
        return _obs.dump(path, reason=reason,
                         extra={"fleet": self.snapshot()})


# ---- rendering (monitor CLI `fleet` subcommand) -----------------------------

def render_fleet(doc: Optional[Dict[str, Any]]) -> str:
    if not doc or not doc.get("replicas"):
        return "(no fleet replicas found)"
    lines = ["-" * 78,
             f"fleet {doc.get('fleet', '?')!r}: "
             f"{len(doc['replicas'])} replica(s)",
             "-" * 78,
             f"{'id':>3} {'endpoint':<21} {'state':<9} {'score':>7} "
             f"{'queue':>5} {'served':>7} {'fail':>5}  models"]
    for rid in sorted(doc["replicas"], key=int):
        r = doc["replicas"][rid]
        state = "draining" if r.get("draining") else (
            "up" if r.get("healthy") else "DEAD")
        lines.append(
            f"{rid:>3} {r['host'] + ':' + str(r['port']):<21} {state:<9} "
            f"{r.get('score', 0.0):>7.3f} {r.get('queue_depth', 0):>5} "
            f"{r.get('served', 0):>7} {r.get('failures', 0):>5}  "
            + (",".join(r.get("tenants", [])) or "-"))
    led = doc.get("ledger")
    if led:
        lines.append(
            f"ledger: issued={led['issued']} settled={led['settled']} "
            f"rejected={led['rejected']} open={led['open']} "
            f"duplicates={led['duplicates']} lost={led['lost']}")
    slo = doc.get("slo")
    if slo:
        burn = slo.get("burn", {})
        if burn:
            worst = max(burn.values())
            lines.append("router SLO burn: " + "  ".join(
                f"{w}s={burn[w]:.3f}"
                for w in sorted(burn, key=int)) +
                ("   <-- over budget" if worst > 1.0 else ""))
    lines.append("-" * 78)
    return "\n".join(lines)
