"""Continuous-batching autoregressive serving (the LLM decode plane).

The ServingEngine batches fixed-shape `run_batch` calls — right for
ResNet/OCR, wrong for decoders, where per-request full-sequence recompute
wastes nearly all decode FLOPs and fixed batches idle between stragglers.
This module serves GPT/ERNIE decoders the way LLM traffic actually wants:

- **KV cache as explicit carry** — `GPTForCausalLM.forward_cached` takes
  fixed-shape cache pages in and returns updated pages, so a decode step
  is one-token work instead of a full-sequence forward.
- **Slot-paged fixed-shape pool** — per layer, one `[num_slots, page_len,
  heads, head_dim]` array pair. Sequences borrow a slot for their
  lifetime; shapes never depend on which slots are live, so steady state
  runs exactly two kinds of cached executables — one prefill per length
  bucket, one decode — with ZERO steady-state compiles (the `jit.*`
  retrace counters stay flat; tests assert it). Pool bytes carry the
  `mem.kv_pool.bytes` census tag.
- **Continuous scheduler** — every decode step admits queued sequences
  into free slots and evicts on EOS/length/deadline, streaming each token
  to the caller the moment it exists (and over the wire as `'PDST'`
  frames via `inference/server.py`). Admission sheds on SLO burn
  (`obs/slo.py`) and queue depth, like the batch engine.
- **Quantized decode arm** — `LLMConfig(quant="int8")` runs the decoder
  matmuls through `quantization.quant_weight_only`; `kv_int8=True` stores
  the pool as int8 with a dequantization scale per slot.

Decode blocks are `decode_block` (=2) tokens wide with only row 0 real:
XLA lowers a rank-1 matmul through a differently-accumulated path, so a
1-wide decode drifts ~1e-6 from the full-sequence forward, while any
block >= 2 is bit-identical to it (tests/test_llm_serving.py proves
logits-exact decode). The junk row's cache write lands one past the live
prefix and is overwritten by the next real token before it can be read.

Reference parity: this is the Paddle-Serving deployment role (PAPER.md
§1 row 8) taken to continuous batching over a paged KV cache — the
vLLM-style iteration-level scheduler, built TPU-first (fixed shapes, two
executables, zero steady-state compiles) instead of kernel-first.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults as _faults
from .. import monitor as _monitor
from .. import nn
from .. import obs as _obs
from ..core import executable as _exe
from ..core import flags as _flags
from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..obs import memory as _mem
from ..obs import slo as _slo
from .engine import (
    DeadlineExceededError, EngineStoppedError, ServerOverloadedError,
    ServingError,
)
from ..utils import syncwatch as _syncwatch

__all__ = ["LLMConfig", "LLMEngine", "LLMStream"]


def _prefill_ladder(max_len: int, declared: Sequence[int] = ()) -> List[int]:
    """Prefill length buckets: declared ones (clamped to max_len), or
    powers of two from 8 up to max_len. One cached executable each."""
    if declared:
        ladder = sorted({int(b) for b in declared if 0 < int(b) <= max_len})
        if ladder:
            return ladder
    ladder, b = [], 8
    while b < max_len:
        ladder.append(b)
        b *= 2
    ladder.append(max_len)
    return sorted(set(ladder))


@dataclass
class LLMConfig:
    """Knobs for the continuous-batching engine (FLAGS_llm_* defaults).

    Pool sizing recipe: bytes = 2 (K and V) * num_layers * num_slots *
    (max_len + decode_block) * heads * head_dim * itemsize — fp32
    itemsize 4, kv_int8 itemsize 1 (+ two f32 scales per slot per
    layer). `LLMEngine.kv_pool_bytes()` reports the real figure and the
    census publishes it as `mem.kv_pool.bytes`."""

    num_slots: int = 8
    max_len: int = 256
    prefill_buckets: Tuple[int, ...] = ()
    max_new_tokens: int = 64
    eos_token_id: Optional[int] = None
    queue_depth: int = 256
    default_deadline_ms: Optional[float] = None
    warmup_on_start: bool = True
    quant: str = "off"          # "off" | "int8" weight-only decoder matmuls
    kv_int8: bool = False
    # block width of one decode step; >= 2 keeps decode bit-identical to
    # the full-sequence forward (see module docstring)
    decode_block: int = 2
    idle_park_s: float = 0.02   # scheduler nap when no work is queued

    @classmethod
    def from_flags(cls) -> "LLMConfig":
        buckets: Tuple[int, ...] = ()
        raw = str(_flags.flag("llm_prefill_buckets") or "").strip()
        if raw:
            buckets = tuple(int(p) for p in raw.split(",") if p.strip())
        ddl = float(_flags.flag("llm_default_deadline_ms"))
        return cls(
            num_slots=int(_flags.flag("llm_num_slots")),
            max_len=int(_flags.flag("llm_max_len")),
            prefill_buckets=buckets,
            max_new_tokens=int(_flags.flag("llm_max_new_tokens")),
            queue_depth=int(_flags.flag("llm_queue_depth")),
            default_deadline_ms=ddl if ddl > 0 else None,
            warmup_on_start=bool(_flags.flag("llm_warmup")),
            quant=str(_flags.flag("llm_quant")),
            kv_int8=bool(_flags.flag("llm_kv_int8")),
        )


class LLMStream:
    """Per-request handle: tokens stream into it as the scheduler emits
    them; iterate to consume incrementally, or `result()` to wait for the
    terminal status. Terminal statuses: "done" (EOS or token budget),
    "deadline", "error" (injected/model fault), "stopped" (engine shut
    down before completion)."""

    def __init__(self, request_id: int, on_token: Optional[Callable] = None):
        self.request_id = request_id
        self.tokens: List[int] = []
        self.status = "queued"
        self.error: Optional[str] = None
        self._on_token = on_token
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()

    # scheduler-side
    def _emit(self, tok: int) -> None:
        self.tokens.append(tok)
        self._q.put(tok)
        if self._on_token is not None:
            try:
                self._on_token(len(self.tokens) - 1, tok)
            except Exception:
                pass  # a broken callback must not kill the scheduler

    def _finish(self, status: str, error: Optional[str] = None) -> None:
        if self._done.is_set():
            return
        self.status = status
        self.error = error
        self._done.set()
        self._q.put(None)

    # consumer-side
    def __iter__(self):
        return self.iter()

    def iter(self, timeout: Optional[float] = 600.0):
        """Yield tokens as they arrive until the stream terminates."""
        while True:
            tok = self._q.get(timeout=timeout)
            if tok is None:
                return
            yield tok

    def result(self, timeout: Optional[float] = None) -> Tuple[str, List[int]]:
        """(terminal status, all tokens); raises TimeoutError on wait."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in flight")
        return self.status, list(self.tokens)

    def done(self) -> bool:
        return self._done.is_set()


@dataclass
class _Seq:
    stream: LLMStream
    prompt: np.ndarray
    max_new: int
    deadline: Optional[float]          # absolute monotonic, or None
    submit_t: float
    slot: int = -1
    pos: int = 0                       # tokens cached so far
    last_token: int = 0
    last_emit_t: float = 0.0
    admit_t: float = 0.0


class _PrefillNet(nn.Layer):
    """One prefill executable per length bucket: (tokens [B, Lb],
    lengths [B]) -> (first greedy token [B], last-position logits [B, V],
    fresh KV pages, [int8 scales]). Pages are created inside the trace so
    the wire signature is just the token block."""

    def __init__(self, lm, page_len: int, kv_int8: bool):
        super().__init__()
        self.lm = lm
        self._page_len = page_len
        self._kv_int8 = kv_int8

    def forward(self, tokens, lengths):
        import jax.numpy as jnp

        from ..ops._dispatch import run_op
        from ..ops.creation import zeros
        from ..ops.manipulation import cast
        from ..ops.search import argmax

        b = tokens.shape[0]
        dtype = "int8" if self._kv_int8 else "float32"
        pages = self.lm.gpt.init_kv_cache(b, self._page_len, dtype=dtype)
        positions = zeros([b], dtype="int32")
        logits, kv, scales = self.lm.forward_cached(tokens, pages, positions)

        def _last(la, ln):
            idx = (ln - 1).astype(jnp.int32)[:, None, None]
            return jnp.take_along_axis(la, idx, axis=1)[:, 0]

        last = run_op(_last, [logits, lengths], "llm_last_logits")
        first = cast(argmax(last, axis=-1), "int32")
        outs = [first, last]
        for k, v in kv:
            outs += [k, v]
        if self._kv_int8:
            for ks, vs in scales:
                outs += [ks, vs]
        return tuple(outs)


class _DecodeNet(nn.Layer):
    """THE decode executable: one fixed-shape step for the whole pool.
    (tokens [S], positions [S], *pool state) -> (next greedy token [S],
    logits [S, V], updated pool pages). Free slots ride along as masked
    junk rows — occupancy never changes the signature."""

    def __init__(self, lm, num_layers: int, block: int, kv_int8: bool):
        super().__init__()
        self.lm = lm
        self._n = num_layers
        self._block = block
        self._kv_int8 = kv_int8

    def forward(self, tokens, positions, *state):
        import jax.numpy as jnp

        from ..ops._dispatch import run_op
        from ..ops.manipulation import cast
        from ..ops.search import argmax

        n, block = self._n, self._block
        kv = [(state[2 * i], state[2 * i + 1]) for i in range(n)]
        scales = None
        if self._kv_int8:
            off = 2 * n
            scales = [(state[off + 2 * i], state[off + 2 * i + 1])
                      for i in range(n)]
        # [S] -> [S, block]: row 0 real, the rest padding (bit-exactness
        # trick — see module docstring)
        blk = run_op(
            lambda t: jnp.broadcast_to(t[:, None], (t.shape[0], block)),
            [tokens], "llm_decode_block")
        logits, kv, _ = self.lm.forward_cached(blk, kv, positions, scales)
        last = logits[:, 0]
        nxt = cast(argmax(last, axis=-1), "int32")
        outs = [nxt, last]
        for k, v in kv:
            outs += [k, v]
        return tuple(outs)


class LLMEngine:
    """Continuous-batching scheduler over a slot-paged KV-cache pool.

    `submit()` is thread-safe and returns an `LLMStream` immediately; a
    single scheduler thread owns the pool and runs the admit -> decode ->
    evict loop. See LLMConfig for sizing and the module docstring for the
    executable-count invariant."""

    _FAULT_SITE = "llm.decode"

    def __init__(self, model, config: Optional[LLMConfig] = None):
        from ..models.gpt import GPTForCausalLM, GPTModel
        cfg = config or LLMConfig.from_flags()
        if isinstance(model, GPTModel):
            model = GPTForCausalLM(model)
        if not hasattr(model, "forward_cached"):
            raise ServingError(
                "LLMEngine needs a model with a cached-attention path "
                "(GPTForCausalLM / GPTModel)")
        self.config = cfg
        self.lm = model
        self.lm.eval()  # serving path: dropout off, rng-stable
        if cfg.quant == "int8":
            from ..quantization import quant_weight_only
            quant_weight_only(self.lm)
        elif cfg.quant not in ("", "off"):
            raise ServingError(f"unknown llm quant arm {cfg.quant!r}")

        gpt = self.lm.gpt
        attn = gpt.layers[0].attention
        self._n_layers = len(gpt.layers)
        self._heads, self._head_dim = attn.num_heads, attn.head_dim
        self._page_len = cfg.max_len + cfg.decode_block
        self.buckets = _prefill_ladder(cfg.max_len, cfg.prefill_buckets)

        self._prefill = _PrefillNet(self.lm, self._page_len, cfg.kv_int8)
        self._decode = _DecodeNet(self.lm, self._n_layers,
                                  cfg.decode_block, cfg.kv_int8)
        from ..jit import to_static
        to_static(self._prefill)
        to_static(self._decode)

        import jax.numpy as jnp
        s = cfg.num_slots
        shape = (s, self._page_len, self._heads, self._head_dim)
        kdt = jnp.int8 if cfg.kv_int8 else jnp.float32
        self._pool: List[Tensor] = []   # k0, v0, k1, v1, ...
        for _ in range(self._n_layers):
            self._pool += [Tensor(jnp.zeros(shape, kdt)),
                           Tensor(jnp.zeros(shape, kdt))]
        self._scales: List[Tensor] = []  # ks0, vs0, ... ([S] f32 per slot)
        if cfg.kv_int8:
            for _ in range(self._n_layers):
                self._scales += [Tensor(jnp.ones((s,), jnp.float32)),
                                 Tensor(jnp.ones((s,), jnp.float32))]

        self._free: List[int] = list(range(s))
        self._active: Dict[int, _Seq] = {}
        self._pending: "collections.deque[_Seq]" = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._next_id = 0
        self._counters = {"requests": 0, "completed": 0, "shed": 0,
                          "evictions.eos": 0, "evictions.length": 0,
                          "evictions.deadline": 0, "evictions.error": 0}
        self._warm_ms = 0.0

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> "LLMEngine":
        if self._thread is not None:
            return self
        if self.config.warmup_on_start:
            self._warmup()
        self._thread = _syncwatch.Thread(target=self._run, daemon=True,
                                        name="llm-scheduler")
        self._thread.start()
        return self

    def _warmup(self) -> None:
        """Trace+compile every prefill bucket and the decode step up
        front so steady-state serving performs zero compiles."""
        import jax.numpy as jnp
        t0 = time.monotonic()
        with no_grad():
            for lb in self.buckets:
                self._prefill(Tensor(jnp.zeros((1, lb), jnp.int32)),
                              Tensor(jnp.ones((1,), jnp.int32)))
            s = self.config.num_slots
            self._decode(Tensor(jnp.zeros((s,), jnp.int32)),
                         Tensor(jnp.zeros((s,), jnp.int32)),
                         *self._pool, *self._scales)
        self._warm_ms = (time.monotonic() - t0) * 1000.0
        if _monitor._ENABLED:
            _monitor.gauge_set("llm.warm_start_ms", self._warm_ms)
            _monitor.count("llm.warmup_runs", len(self.buckets) + 1)

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        if drain and self._thread is not None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._pending and not self._active:
                        break
                time.sleep(0.01)
        with self._work:
            self._stopped = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            leftovers = list(self._pending) + list(self._active.values())
            self._pending.clear()
            self._active.clear()
            self._free = list(range(self.config.num_slots))
        for seq in leftovers:
            seq.stream._finish("stopped", "engine stopped")
        # Break the StaticFunction <-> jax.jit reference cycle so the
        # model weights and KV pool become collectable once the engine
        # is dropped (the cycle runs through C-level jit wrappers the
        # garbage collector cannot traverse).
        for net in (self._prefill, self._decode):
            fwd = getattr(net, "forward", None)
            if hasattr(fwd, "release"):
                fwd.release()

    # ---- submission --------------------------------------------------------

    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               on_token: Optional[Callable] = None) -> LLMStream:
        """Queue one generation; returns its LLMStream immediately.
        Sheds with ServerOverloadedError on queue depth or SLO burn
        (`FLAGS_slo_shed_burn`), like ServingEngine.submit."""
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ServingError("empty prompt")
        if prompt.size > self.buckets[-1]:
            raise ServingError(
                f"prompt length {prompt.size} exceeds the largest prefill "
                f"bucket {self.buckets[-1]} (raise FLAGS_llm_max_len)")
        if self._stopped or self._thread is None:
            raise EngineStoppedError("LLM engine not running")
        if _slo._ENABLED and _slo.should_shed():
            self._counters["shed"] += 1
            if _monitor._ENABLED:
                _monitor.count("llm.shed")
            _slo.record_request(None, _slo.OUTCOME_REJECTED)
            raise ServerOverloadedError("shedding on SLO burn rate")
        budget = self.config.max_len - int(prompt.size)
        max_new = min(int(max_new_tokens or self.config.max_new_tokens),
                      max(budget, 1))
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        now = time.monotonic()
        deadline = now + deadline_ms / 1000.0 if deadline_ms else None
        with self._work:
            if len(self._pending) >= self.config.queue_depth:
                self._counters["shed"] += 1
                if _monitor._ENABLED:
                    _monitor.count("llm.shed")
                raise ServerOverloadedError(
                    f"llm queue full ({self.config.queue_depth})")
            self._next_id += 1
            stream = LLMStream(self._next_id, on_token)
            seq = _Seq(stream=stream, prompt=prompt, max_new=max_new,
                       deadline=deadline, submit_t=now)
            self._pending.append(seq)
            self._counters["requests"] += 1
            self._work.notify()
        if _monitor._ENABLED:
            _monitor.count("llm.requests")
        return stream

    def generate(self, prompt_ids: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 timeout: float = 600.0) -> List[int]:
        """Blocking convenience wrapper: submit + wait; raises the
        deadline/error terminal statuses as serving exceptions."""
        status, toks = self.submit(
            prompt_ids, max_new_tokens, deadline_ms).result(timeout)
        if status == "deadline":
            raise DeadlineExceededError("generation deadline exceeded")
        if status != "done":
            raise ServingError(f"generation {status}")
        return toks

    # ---- scheduler ---------------------------------------------------------

    def _run(self) -> None:
        with no_grad():
            while True:
                with self._work:
                    if self._stopped:
                        return
                    if not self._pending and not self._active:
                        self._work.wait(timeout=self.config.idle_park_s)
                        if self._stopped:
                            return
                    pending_now = bool(self._pending)
                if pending_now:
                    self._admit()
                if self._active:
                    try:
                        self._step()
                    except Exception as e:  # scheduler must survive
                        self._evict_all("error",
                                        f"{type(e).__name__}: {e}")

    def _admit(self) -> None:
        while True:
            with self._lock:
                if not self._free or not self._pending:
                    return
                seq = self._pending.popleft()
                slot = self._free.pop()
            now = time.monotonic()
            if seq.deadline is not None and now > seq.deadline:
                with self._lock:
                    self._free.append(slot)
                self._finish(seq, "deadline", "expired before admission")
                continue
            seq.slot, seq.admit_t = slot, now
            self._prefill_into(seq)

    def _prefill_into(self, seq: _Seq) -> None:
        import jax
        import jax.numpy as jnp

        from ..ops._dispatch import run_op

        cfg = self.config
        plen = int(seq.prompt.size)
        lb = next(b for b in self.buckets if b >= plen)
        padded = np.zeros((1, lb), np.int32)
        padded[0, :plen] = seq.prompt
        outs = self._prefill(Tensor(jnp.asarray(padded)),
                             Tensor(jnp.full((1,), plen, jnp.int32)))
        first = int(np.asarray(outs[0].numpy())[0])
        slot_t = Tensor(jnp.asarray(seq.slot, jnp.int32))

        def _row(pool, row, s):
            return jax.lax.dynamic_update_slice(pool, row, (s, 0, 0, 0))

        def _cell(vec, val, s):
            return jax.lax.dynamic_update_slice(vec, val, (s,))

        pages = outs[2:2 + 2 * self._n_layers]
        for i, page in enumerate(pages):
            self._pool[i] = run_op(_row, [self._pool[i], page, slot_t],
                                   "llm_slot_write")
        if cfg.kv_int8:
            svals = outs[2 + 2 * self._n_layers:]
            for i, sv in enumerate(svals):
                self._scales[i] = run_op(_cell, [self._scales[i], sv, slot_t],
                                         "llm_scale_write")
        now = time.monotonic()
        seq.pos = plen
        seq.last_token = first
        seq.last_emit_t = now
        seq.stream.status = "running"
        seq.stream._emit(first)
        with self._lock:
            self._active[seq.slot] = seq
        if _monitor._ENABLED:
            _monitor.count("llm.prefill.requests")
            _monitor.count("llm.tokens_generated")
            _monitor.observe("llm.queue_wait", seq.admit_t - seq.submit_t)
            _monitor.observe("llm.ttft_ms", (now - seq.submit_t) * 1000.0)
            _monitor.gauge_set("llm.slots_active", len(self._active))
        self._retag_pool()
        # a one-token budget (or instant EOS) finishes without decoding
        if first == cfg.eos_token_id:
            self._evict(seq, "eos")
        elif len(seq.stream.tokens) >= seq.max_new:
            self._evict(seq, "length")

    def _step(self) -> None:
        """One decode step for every active slot: fault drill, dispatch,
        emit, evict. Fixed shapes — occupancy is data, not signature."""
        import jax.numpy as jnp

        cfg = self.config
        now = time.monotonic()
        with self._lock:
            live = sorted(self._active.items())
        # the llm.decode fault site is checked once per in-flight
        # sequence so an injected error takes down exactly one of them
        for slot, seq in live:
            if seq.deadline is not None and now > seq.deadline:
                self._evict(seq, "deadline")
                continue
            if _faults._ENABLED:
                try:
                    _faults.check(self._FAULT_SITE)
                except Exception as e:
                    self._evict(seq, "error",
                                f"{type(e).__name__}: {e}")
        with self._lock:
            live = sorted(self._active.items())
        if not live:
            return
        s = cfg.num_slots
        toks = np.zeros((s,), np.int32)
        pos = np.zeros((s,), np.int32)
        for slot, seq in live:
            toks[slot] = seq.last_token
            pos[slot] = seq.pos

        def _dispatch():
            report = lambda: {"kv_pool_bytes": self.kv_pool_bytes()}
            with _exe.dispatch_guard("llm_decode", report=report), \
                    _monitor.span("llm.decode_step"):
                return self._decode(Tensor(jnp.asarray(toks)),
                                    Tensor(jnp.asarray(pos)),
                                    *self._pool, *self._scales)

        if _obs._TL_ENABLED and not _obs.in_phase():
            with _obs.timeline().phase("decode_step"):
                outs = _dispatch()
        else:
            outs = _dispatch()
        nxt = np.asarray(outs[0].numpy())
        self._pool = list(outs[2:2 + 2 * self._n_layers])
        now = time.monotonic()
        for slot, seq in live:
            tok = int(nxt[slot])
            seq.pos += 1
            seq.last_token = tok
            seq.stream._emit(tok)
            if _monitor._ENABLED:
                _monitor.count("llm.tokens_generated")
                _monitor.observe("llm.inter_token_ms",
                                 (now - seq.last_emit_t) * 1000.0)
            seq.last_emit_t = now
            if tok == cfg.eos_token_id:
                self._evict(seq, "eos")
            elif len(seq.stream.tokens) >= seq.max_new \
                    or seq.pos >= cfg.max_len:
                self._evict(seq, "length")
            elif seq.deadline is not None and now > seq.deadline:
                self._evict(seq, "deadline")
        if _monitor._ENABLED:
            _monitor.count("llm.decode.steps")
            _monitor.gauge_set("llm.slots_active", len(self._active))
        self._retag_pool()

    # ---- eviction / bookkeeping --------------------------------------------

    def _evict(self, seq: _Seq, reason: str, error: Optional[str] = None) -> None:
        """Free the sequence's slot and terminate its stream. The pool
        row needs no scrub: free slots are never read (the validity mask
        keys off per-row positions) and the next prefill replaces the
        whole page."""
        with self._lock:
            if self._active.pop(seq.slot, None) is not None:
                self._free.append(seq.slot)
        status = {"eos": "done", "length": "done"}.get(reason, reason)
        self._counters[f"evictions.{reason}"] = \
            self._counters.get(f"evictions.{reason}", 0) + 1
        if _monitor._ENABLED:
            _monitor.count(f"llm.evictions.{reason}")
        self._finish(seq, status, error)

    def _evict_all(self, status: str, error: str) -> None:
        with self._lock:
            live = list(self._active.values())
            self._active.clear()
            self._free = list(range(self.config.num_slots))
        for seq in live:
            self._counters["evictions.error"] += 1
            if _monitor._ENABLED:
                _monitor.count("llm.evictions.error")
            self._finish(seq, status, error)

    def _finish(self, seq: _Seq, status: str, error: Optional[str]) -> None:
        latency = time.monotonic() - seq.submit_t
        self._counters["completed"] += 1
        if _monitor._ENABLED:
            _monitor.count("llm.completed")
            _monitor.observe("llm.e2e_latency", latency)
        if _slo._ENABLED:
            outcome = {"done": _slo.OUTCOME_OK,
                       "deadline": _slo.OUTCOME_DEADLINE}.get(
                           status, _slo.OUTCOME_ERROR)
            _slo.record_request(
                latency if outcome == _slo.OUTCOME_OK else None, outcome)
        seq.stream._finish(status, error)

    def _retag_pool(self) -> None:
        if _mem._ENABLED:
            _mem.tag("kv_pool",
                     [t._value for t in (*self._pool, *self._scales)],
                     origin="LLMEngine")

    # ---- introspection -----------------------------------------------------

    def kv_pool_bytes(self) -> int:
        total = 0
        for t in (*self._pool, *self._scales):
            v = t._value
            total += int(getattr(v, "nbytes", 0) or
                         int(np.prod(v.shape)) * v.dtype.itemsize)
        return total

    def stats(self) -> dict:
        with self._lock:
            active, free, queued = (len(self._active), len(self._free),
                                    len(self._pending))
        return {
            "slots": self.config.num_slots, "active": active, "free": free,
            "queued": queued, "buckets": list(self.buckets),
            "page_len": self._page_len, "kv_pool_bytes": self.kv_pool_bytes(),
            "kv_int8": self.config.kv_int8, "quant": self.config.quant,
            "warm_start_ms": self._warm_ms,
            "counters": dict(self._counters),
        }
