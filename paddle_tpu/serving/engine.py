"""ServingEngine: dynamic batching between the wire protocols and the
Predictor.

Role (reference `paddle/fluid/inference/` deployment stack, rebuilt
TPU-native in the Clipper/Triton dynamic-batching shape): concurrent
single-item requests are coalesced into padded shape-bucket batches so the
accelerator sees large, pre-compiled launches instead of batch-1 dispatches
— and robustness is part of the contract, not an afterthought:

  - bounded request queue with EXPLICIT overload rejection
    (`ServerOverloadedError`, its own wire status code — a client can tell
    backpressure from failure and retry elsewhere)
  - per-request deadlines: expired requests are dropped BEFORE batching
    (`DeadlineExceededError`), so a dead client never occupies MXU rows
  - shape buckets (declared or learned) + startup warmup: steady-state
    serving never triggers an XLA compile
  - graceful drain on shutdown; health/stats snapshot for probes
  - full `paddle_tpu.monitor` instrumentation (queue-depth gauge,
    queue-wait/e2e histograms, batch-size histogram, padding-waste and
    rejection/expiry counters) so one Prometheus scrape covers the path

Thread model: `submit()` is called from any number of protocol threads;
`num_workers` worker loops assemble batches per bucket lane; the actual
predictor invocation is serialized by a dispatch lock (the XLA executable
saturates the chip — overlapping workers only overlap host pre/post work).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults as _faults
from .. import monitor as _monitor
from .. import obs as _obs
from ..obs import memory as _mem
from ..obs import slo as _slo
from ..obs import trace as _trace
from ..core import compile_cache as _cc
from ..core import executable as _exe
from ..core import flags as _flags
from .bucket import BucketSet, ShapeBucket, default_batch_sizes, signature_of
from ..utils import syncwatch as _syncwatch

__all__ = [
    "EngineConfig", "ServingEngine", "ResponseFuture",
    "ServingError", "ServerOverloadedError", "DeadlineExceededError",
    "EngineStoppedError", "NoBucketError",
]


class ServingError(RuntimeError):
    """Base of every engine-raised request failure."""
    wire_status = 1


class ServerOverloadedError(ServingError):
    """Queue at capacity: explicit backpressure, NOT a failure — the
    client should back off and retry (wire status 2)."""
    wire_status = 2


class DeadlineExceededError(ServingError):
    """The request's deadline passed before it reached the accelerator
    (wire status 3)."""
    wire_status = 3


class EngineStoppedError(ServingError):
    """Submitted after stop(): the engine is draining or down. Wire
    status 2 (overloaded/retryable), NOT 1: a draining replica is
    healthy backpressure — the client should fail over to another
    replica, exactly like queue-full rejection."""
    wire_status = 2


class NoBucketError(ServingError):
    """No declared bucket accepts this shape and learning is disabled."""
    wire_status = 1


# an overloaded engine dumps the flight recorder (rate-limited — one dump
# per FLAGS_obs_dump_min_interval_s, not one per rejected request): the
# black box shows queue depth, batch sizes, and latency counters leading
# into the overload
_obs.register_dump_trigger(ServerOverloadedError, "serving_overload")


class ResponseFuture:
    """Per-request response slot resolved by a worker thread."""

    __slots__ = ("_event", "_outputs", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._outputs: Optional[List[np.ndarray]] = None
        self._error: Optional[BaseException] = None

    def _set_result(self, outputs: List[np.ndarray]) -> None:
        self._outputs = outputs
        self._event.set()

    def _set_exception(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def exception(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("response not ready")
        return self._error

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        if not self._event.wait(timeout):
            raise TimeoutError("response not ready")
        if self._error is not None:
            raise self._error
        return self._outputs


class _Request:
    __slots__ = ("inputs", "rows", "sig", "bucket", "future",
                 "enqueue_t", "deadline", "trace_ctx", "qw_span")

    def __init__(self, inputs, rows, sig, bucket, deadline,
                 trace_ctx=None):
        self.inputs = inputs
        self.rows = rows
        self.sig = sig
        self.bucket = bucket
        self.future = ResponseFuture()
        self.enqueue_t = time.monotonic()
        self.deadline = deadline  # absolute monotonic, or None
        self.trace_ctx = trace_ctx  # obs.trace.TraceContext, or None
        # queue_wait child span: opened at enqueue, closed at dispatch
        # pick-up (ok) or expiry (deadline status -> protected trace ring)
        self.qw_span = _trace.server_span("serving.queue_wait", trace_ctx)


@dataclass
class EngineConfig:
    """Knobs, each also exported as a FLAGS_serving_* flag (flags.cc role);
    `EngineConfig.from_flags()` is what PredictorServer uses by default."""

    max_batch_size: int = 8
    batch_timeout_ms: float = 2.0       # max coalescing wait per batch
    queue_depth: int = 256              # pending-request cap (backpressure)
    default_deadline_ms: float = 0.0    # 0 = no implicit deadline
    num_workers: int = 1
    learn_buckets: bool = True          # novel signatures become buckets
    warmup_on_start: bool = True        # pre-compile declared buckets
    batch_sizes: Optional[Sequence[int]] = field(default=None)

    @classmethod
    def from_flags(cls) -> "EngineConfig":
        return cls(
            max_batch_size=int(_flags.flag("serving_max_batch_size")),
            batch_timeout_ms=float(_flags.flag("serving_batch_timeout_ms")),
            queue_depth=int(_flags.flag("serving_queue_depth")),
            default_deadline_ms=float(
                _flags.flag("serving_default_deadline_ms")),
            num_workers=int(_flags.flag("serving_num_workers")),
            learn_buckets=bool(_flags.flag("serving_learn_buckets")),
            warmup_on_start=bool(_flags.flag("serving_warmup")),
        )

    def ladder(self) -> Tuple[int, ...]:
        return tuple(self.batch_sizes) if self.batch_sizes else \
            default_batch_sizes(self.max_batch_size)


class ServingEngine:
    """Dynamic batcher + worker loop(s) over one Predictor (or any callable
    of numpy arrays returning an array / list of arrays)."""

    def __init__(self, predictor, config: Optional[EngineConfig] = None):
        self.config = config or EngineConfig.from_flags()
        self.predictor = predictor
        self._call = self._make_call(predictor)
        self.buckets = BucketSet(learn=self.config.learn_buckets,
                                 default_batch_sizes_=self.config.ladder())
        # a Predictor knows its artifact's exported signature — those
        # shapes become declared buckets automatically (a saved StableHLO
        # artifact only accepts its exported batch; requests pad up to it)
        derive = getattr(predictor, "serving_buckets", None)
        if callable(derive):
            for item_shapes, dtypes, sizes in derive(self.config.ladder()):
                self.buckets.declare(item_shapes, dtypes, sizes)
        self._cv = threading.Condition()
        self._lanes: Dict[Any, "List[_Request]"] = {}
        self._pending = 0
        self._inflight = 0
        self._stopping = False
        self._workers: List[threading.Thread] = []
        self._dispatch_lock = _syncwatch.lock(
            "engine.ServingEngine._dispatch_lock")
        # executable substrate: (batch, item-sig) ledger — novel → compiles.
        # The predictor's own to_static capture owns retrace accounting and
        # the persistent-cache hookup; the engine ledger keeps the serving-
        # local compile/pool bookkeeping (retrace=False at note()).
        self._ledger = _exe.ExecutableLedger("serving_bucket")
        self._warm_start_ms: Optional[float] = None
        # per-tenant SLO isolation (fleet tier): when a ModelTenant owns
        # this engine it installs its OWN SloPlane here — outcomes then
        # account against THAT tenant's error budget (in addition to the
        # global flag-wired plane), so one hot model's burn cannot hide
        # in (or pollute) its neighbours'
        self.slo_plane: Optional[_slo.SloPlane] = None
        self._counts: Dict[str, int] = {
            "requests": 0, "completed": 0, "failed": 0, "rejected": 0,
            "expired": 0, "batches": 0, "rows": 0, "padded_rows": 0,
            "padding_waste_elems": 0, "compiles": 0, "warmup_runs": 0,
        }

    # ---- construction helpers ----
    @staticmethod
    def _make_call(predictor) -> Callable[[List[np.ndarray]],
                                          List[np.ndarray]]:
        run_batch = getattr(predictor, "run_batch", None)
        if callable(run_batch):
            return run_batch

        def call(arrays: List[np.ndarray]) -> List[np.ndarray]:
            out = predictor(*arrays)
            outs = out if isinstance(out, (list, tuple)) else [out]
            if _mem._ENABLED:
                # outs are (wrapped) device arrays until np.asarray below;
                # the predictor keeps the last set alive in its results
                # cache — the census should attribute them, not call them
                # "other"
                _mem.tag("serving_bucket", outs, origin="ServingEngine")
            return [np.asarray(o) for o in outs]

        return call

    # ---- bucket declaration / warmup ----
    def declare_bucket(self, item_shapes, dtypes,
                       batch_sizes=None) -> ShapeBucket:
        """Pre-declare a padded lane (shapes are per-item, no batch dim).
        Declared buckets are what warmup() compiles."""
        return self.buckets.declare(item_shapes, dtypes,
                                    batch_sizes or self.config.ladder())

    def warmup(self) -> int:
        """Run the predictor once per (bucket, batch size) on zeros so
        steady-state serving never compiles. Returns runs performed.

        With `FLAGS_compile_cache_dir` set the predictor's capture rides
        the persistent executable cache, so a replica whose programs a
        prior process already compiled warms in deserialize time instead
        of compile time — `stats()["warm_start_ms"]` plus the
        `compile_cache` hit/miss counters tell a router which one it got."""
        t0 = time.time()
        runs = 0
        for bucket in self.buckets.buckets():
            for bs in bucket.batch_sizes:
                arrays = [np.zeros((bs,) + shape, dtype=np.dtype(dt))
                          for shape, dt in zip(bucket.item_shapes,
                                               bucket.dtypes)]
                self._dispatch_to_predictor(bucket, bs, arrays)
                runs += 1
        self._warm_start_ms = (time.time() - t0) * 1000.0
        self._bump("warmup_runs", runs)
        if _monitor._ENABLED and runs:
            _monitor.count("serving.warmup_runs", runs)
            _monitor.gauge_set("serving.warm_start_ms", self._warm_start_ms)
        return runs

    # ---- lifecycle ----
    def start(self) -> "ServingEngine":
        if self._workers:
            return self
        self._stopping = False
        if self.config.warmup_on_start:
            self.warmup()
        for i in range(max(1, self.config.num_workers)):
            t = _syncwatch.Thread(target=self._worker_loop,
                                 name=f"serving-worker-{i}", daemon=True)
            t.start()
            self._workers.append(t)
        return self

    def stop(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Stop accepting requests; drain=True completes what is queued,
        drain=False fails queued futures with EngineStoppedError."""
        with self._cv:
            self._stopping = True
            if not drain:
                for lane in self._lanes.values():
                    for req in lane:
                        req.future._set_exception(EngineStoppedError(
                            "engine stopped before dispatch"))
                        self._pending -= 1
                    lane.clear()
            self._cv.notify_all()
        deadline = time.monotonic() + timeout
        for t in self._workers:
            t.join(max(0.0, deadline - time.monotonic()))
        self._workers = []
        self._set_queue_gauge()

    @property
    def running(self) -> bool:
        return bool(self._workers) and not self._stopping

    # ---- request intake ----
    def submit(self, inputs: Sequence[np.ndarray],
               deadline_ms: Optional[float] = None,
               trace_ctx=None) -> ResponseFuture:
        """Enqueue one request (arrays share a leading batch dim, usually
        1). Raises ServerOverloadedError / EngineStoppedError /
        NoBucketError / ValueError synchronously; everything later lands
        on the returned future. `trace_ctx` (an obs.trace.TraceContext,
        normally the server-side request span's context) parents the
        engine's queue_wait/batch/dispatch spans."""
        arrays = [np.ascontiguousarray(a) for a in inputs]
        if not arrays:
            raise ValueError("empty request")
        rows = int(arrays[0].shape[0]) if arrays[0].ndim else 0
        if rows < 1 or any(a.ndim == 0 or a.shape[0] != rows
                           for a in arrays):
            raise ValueError(
                "request inputs must share a leading batch dim >= 1")
        if _slo._ENABLED and _slo.should_shed():
            # burn-rate admission control (FLAGS_slo_shed_burn): shed
            # explicitly while the short-window burn is over threshold —
            # deliberate small budget spend instead of a brown-out
            self._bump("rejected")
            if _monitor._ENABLED:
                _monitor.count("serving.rejected")
                _monitor.count("serving.shed")
            self._slo_record(None, _slo.OUTCOME_REJECTED)
            raise ServerOverloadedError(
                "shedding: SLO error-budget burn rate over "
                "FLAGS_slo_shed_burn; back off and retry")
        sig = signature_of(arrays)
        bucket = self.buckets.resolve(sig)
        if bucket is None:
            self._bump("rejected")
            if _monitor._ENABLED:
                _monitor.count("serving.rejected")
            if _slo._ENABLED or self.slo_plane is not None:
                self._slo_record(None, _slo.OUTCOME_REJECTED)
            raise NoBucketError(
                f"no declared bucket accepts {sig} and bucket learning "
                "is disabled (FLAGS_serving_learn_buckets)")
        if rows > bucket.max_batch_size:
            raise ValueError(
                f"request batch {rows} exceeds bucket max "
                f"{bucket.max_batch_size}")
        if deadline_ms is None and self.config.default_deadline_ms > 0:
            deadline_ms = self.config.default_deadline_ms
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms and deadline_ms > 0 else None)
        req = _Request(arrays, rows, sig, bucket, deadline,
                       trace_ctx=trace_ctx)
        try:
            with self._cv:
                if self._stopping:
                    raise EngineStoppedError("engine is stopped/draining")
                if self._pending >= self.config.queue_depth:
                    self._counts["rejected"] += 1
                    if _monitor._ENABLED:
                        _monitor.count("serving.rejected")
                    if _slo._ENABLED or self.slo_plane is not None:
                        self._slo_record(None, _slo.OUTCOME_REJECTED)
                    err = ServerOverloadedError(
                        f"queue at capacity ({self.config.queue_depth} "
                        "pending); back off and retry")
                    if _obs._FR_ENABLED:
                        _obs.record_event(
                            "serving.overload",
                            queue_depth=self.config.queue_depth,
                            pending=self._pending)
                        _obs.dump_on_error(err)
                    raise err
                self._lanes.setdefault(bucket.key(), []).append(req)
                self._pending += 1
                self._counts["requests"] += 1
                self._cv.notify()
        except ServingError:
            req.qw_span.end(status=_trace.STATUS_REJECTED)
            raise
        if _monitor._ENABLED:
            _monitor.count("serving.requests")
        self._set_queue_gauge()
        return req.future

    # ---- worker side ----
    def _worker_loop(self) -> None:
        while True:
            batch, bucket = self._collect_batch()
            if batch is None:
                return
            if batch:
                self._dispatch(bucket, batch)

    def _collect_batch(self):
        """Block for work, pick the lane with the oldest head request,
        coalesce up to max batch within batch_timeout (clipped to the
        earliest member deadline). Returns (None, None) on shutdown,
        ([], None) when everything pulled had expired."""
        cfg = self.config
        with self._cv:
            while self._pending == 0:
                if self._stopping:
                    return None, None
                self._cv.wait(0.05)
            key = min((k for k, lane in self._lanes.items() if lane),
                      key=lambda k: self._lanes[k][0].enqueue_t)
            lane = self._lanes[key]
            bucket = lane[0].bucket
            batch: List[_Request] = []
            rows = 0
            t_close = time.monotonic() + cfg.batch_timeout_ms / 1e3
            while True:
                now = time.monotonic()
                while lane and rows + lane[0].rows <= bucket.max_batch_size:
                    req = lane.pop(0)
                    self._pending -= 1
                    if req.deadline is not None and now > req.deadline:
                        self._expire(req)
                        continue
                    batch.append(req)
                    rows += req.rows
                    if req.deadline is not None:
                        t_close = min(t_close, req.deadline)
                if (rows >= bucket.max_batch_size or self._stopping
                        or not batch):
                    break
                now = time.monotonic()
                if now >= t_close:
                    break
                self._cv.wait(t_close - now)
            self._inflight += len(batch)
        self._set_queue_gauge()
        return batch, bucket

    def _expire(self, req: _Request) -> None:
        self._counts["expired"] += 1
        req.future._set_exception(DeadlineExceededError(
            "deadline expired before dispatch"))
        req.qw_span.end(status=_trace.STATUS_DEADLINE)
        if _slo._ENABLED or self.slo_plane is not None:
            self._slo_record(time.monotonic() - req.enqueue_t,
                             _slo.OUTCOME_DEADLINE)
        if _monitor._ENABLED:
            _monitor.count("serving.deadline_expired")

    def _dispatch(self, bucket: ShapeBucket, batch: List[_Request]) -> None:
        # deadlines re-checked at the last host moment: an entry that
        # expired while the batch was coalescing is dropped BEFORE padding
        now = time.monotonic()
        live = []
        with self._cv:
            for req in batch:
                if req.deadline is not None and now > req.deadline:
                    self._expire(req)
                    self._inflight -= 1
                else:
                    live.append(req)
        if not live:
            return
        batch_span = _trace.NULL_SPAN
        disp_spans = None
        if _trace._ENABLED:
            # the batch belongs to no single trace: it parents onto the
            # OLDEST member's request span and LINKS every member span, so
            # any member's trace reaches the shared coalesce + dispatch
            batch_span = _trace.server_span("serving.batch",
                                            live[0].trace_ctx)
            for r in live:
                if r.trace_ctx is not None:
                    batch_span.link_ctx(r.trace_ctx)
            batch_span.set(rows=sum(r.rows for r in live),
                           requests=len(live))
            # per-member dispatch spans: each trace's waterfall shows the
            # (shared) predictor call it rode
            disp_spans = [_trace.server_span("serving.dispatch",
                                             r.trace_ctx) for r in live]
        for r in live:
            r.qw_span.end()
        try:
            rows = sum(r.rows for r in live)
            bs = bucket.round_up_batch(rows)
            arrays, waste = self._assemble(bucket, live, rows, bs)
            t_disp = time.monotonic()
            outs = self._dispatch_to_predictor(bucket, bs, arrays)
            t_done = time.monotonic()
            if not outs or any(o.shape[:1] != (bs,) for o in outs):
                raise ServingError(
                    f"predictor returned shapes "
                    f"{[getattr(o, 'shape', None) for o in outs]} for a "
                    f"batch of {bs}: the serving engine requires every "
                    "output to keep the leading batch dim")
            off = 0
            for req in live:
                req.future._set_result([o[off:off + req.rows]
                                        for o in outs])
                off += req.rows
            if disp_spans is not None:
                for sp in disp_spans:
                    sp.end()
                batch_span.end(batch=bs)
            self._record_batch(live, rows, bs, waste, t_disp, t_done)
        except ServingError as e:
            self._fail_batch(live, e, disp_spans, batch_span)
        except Exception as e:  # noqa: BLE001 — model errors go to callers
            self._fail_batch(live, e, disp_spans, batch_span)
        finally:
            with self._cv:
                self._inflight -= len(live)

    def _assemble(self, bucket: ShapeBucket, live: List[_Request],
                  rows: int, bs: int):
        arrays: List[np.ndarray] = []
        waste = 0
        for slot, (shape, dt) in enumerate(zip(bucket.item_shapes,
                                               bucket.dtypes)):
            parts = [bucket.pad_item(r.inputs[slot], slot) for r in live]
            if bs > rows:
                parts.append(np.zeros((bs - rows,) + shape,
                                      dtype=np.dtype(dt)))
            col = np.concatenate(parts, axis=0) if len(parts) > 1 \
                else parts[0]
            arrays.append(col)
            item_elems = int(np.prod(shape)) if shape else 1
            real = sum(int(np.prod(r.inputs[slot].shape))
                       for r in live)
            waste += bs * item_elems - real
        return arrays, waste

    def _dispatch_to_predictor(self, bucket: ShapeBucket, bs: int,
                               arrays: List[np.ndarray]) -> List[np.ndarray]:
        sig = (bs,) + bucket.key()
        if self._ledger.note(sig, retrace=False):
            # first time this padded signature reaches the predictor = one
            # XLA compile; in steady state this never fires (warmed up)
            self._bump("compiles")
            if _monitor._ENABLED:
                _monitor.count("serving.compiles")
                _monitor.log_event("serving.compile", batch=bs,
                                   signature=[f"{s}:{d}" for s, d in
                                              bucket.signature])
        with self._dispatch_lock:
            if _faults._ENABLED:
                # injected dispatch failure fails THIS batch's futures
                # (via _dispatch's error path) — the engine itself keeps
                # serving; chaos runs verify exactly that containment
                _faults.check("serving.dispatch")
            # booking only (no compiled() here): the predictor's inner
            # to_static capture counts the actual trace_compile; a nested
            # booking there suppresses its phase, so the wall time books
            # exactly once
            with _exe.booking("serving_bucket"), \
                    _monitor.span("serving.predict"):
                return [np.asarray(o) for o in self._call(arrays)]

    def _fail_batch(self, live: List[_Request], err: BaseException,
                    disp_spans=None, batch_span=_trace.NULL_SPAN) -> None:
        self._bump("failed", len(live))
        if _monitor._ENABLED:
            _monitor.count("serving.failed", len(live))
        msg = f"{type(err).__name__}: {str(err)[:200]}"
        if disp_spans is not None:
            # a dispatch fault (injected conn-reset/timeout included) must
            # close every member's span with error status — a leaked open
            # span is a bug the autouse _no_trace_leak fixture catches
            for sp in disp_spans:
                sp.end(status=_trace.STATUS_ERROR, error=msg)
        batch_span.end(status=_trace.STATUS_ERROR, error=msg)
        if _slo._ENABLED or self.slo_plane is not None:
            for _ in live:
                self._slo_record(None, _slo.OUTCOME_ERROR)
        for req in live:
            req.future._set_exception(err)

    # ---- accounting ----
    def _bump(self, name: str, delta: int = 1) -> None:
        with self._cv:
            self._counts[name] += delta

    def _slo_record(self, latency_s, outcome=_slo.OUTCOME_OK) -> bool:
        """Account one finished request against the global SLO plane AND
        the tenant-owned instance plane (fleet per-tenant isolation).
        Callers gate on `_slo._ENABLED or self.slo_plane is not None` so
        the fully-disabled path stays two attribute checks."""
        bad = False
        if _slo._ENABLED:
            bad = _slo.record_request(latency_s, outcome)
        p = self.slo_plane
        if p is not None:
            bad = p.record(latency_s, outcome) or bad
        return bad

    def _set_queue_gauge(self) -> None:
        if _monitor._ENABLED:
            _monitor.gauge_set("serving.queue_depth", self._pending)

    def _record_batch(self, live, rows, bs, waste, t_disp, t_done) -> None:
        with self._cv:
            self._counts["completed"] += len(live)
            self._counts["batches"] += 1
            self._counts["rows"] += rows
            self._counts["padded_rows"] += bs - rows
            self._counts["padding_waste_elems"] += waste
        if _slo._ENABLED or self.slo_plane is not None:
            for req in live:
                bad = self._slo_record(t_done - req.enqueue_t)
                if bad and _trace._ENABLED and req.trace_ctx is not None:
                    # over the latency objective: drop an instant marker
                    # span so tail sampling keeps this trace (protected
                    # ring) even though every stage span closed ok
                    _trace.server_span(
                        "serving.slo_violation", req.trace_ctx,
                        attrs={"e2e_ms": (t_done - req.enqueue_t) * 1e3},
                    ).end(status=_trace.STATUS_SLO_VIOLATION)
        if not _monitor._ENABLED:
            return
        _monitor.count("serving.completed", len(live))
        _monitor.count("serving.batches")
        _monitor.count("serving.padded_rows", bs - rows)
        _monitor.count("serving.padding_waste_elems", waste)
        _monitor.observe("serving.batch_size", rows)
        for req in live:
            _monitor.observe("serving.queue_wait", t_disp - req.enqueue_t)
            _monitor.observe("serving.e2e_latency", t_done - req.enqueue_t)

    # ---- health / stats ----
    def bucket_pool_bytes(self) -> int:
        """Bytes the warm bucket pool pins on device: one padded input set
        per (batch size, item signature) ever dispatched — each signature
        keeps a compiled executable whose argument buffers steady-state
        serving re-feeds. Gauged as `serving.bucket_pool.bytes`; the mem
        census' `serving_bucket` tag covers the live output side."""
        total = 0
        for sig in self._ledger.seen_sigs():
            bs = int(sig[0])
            for shape, dt in sig[1:]:
                elems = int(np.prod(shape)) if shape else 1
                total += bs * elems * np.dtype(dt).itemsize
        return total

    def stats(self) -> Dict[str, Any]:
        """Health snapshot for probes and the wire health endpoint."""
        with self._cv:
            counts = dict(self._counts)
            pending = self._pending
            inflight = self._inflight
        pool_bytes = self.bucket_pool_bytes()
        if _monitor._ENABLED:
            _monitor.gauge_set("serving.bucket_pool.bytes", pool_bytes)
        return {
            "running": self.running,
            "queue_depth": pending,
            "inflight": inflight,
            "queue_capacity": self.config.queue_depth,
            "max_batch_size": self.config.max_batch_size,
            "batch_timeout_ms": self.config.batch_timeout_ms,
            "workers": len(self._workers),
            "buckets": [b.describe() for b in self.buckets.buckets()],
            "bucket_pool_bytes": pool_bytes,
            "counters": counts,
            # cold/warm replica discrimination for routers: how long this
            # replica's bucket warm-up took and whether its executables
            # came off disk (hits) or compiled fresh (misses)
            "warm_start_ms": self._warm_start_ms,
            "compile_cache": _cc.stats(),
            # error-budget burn for the replica router (None = no SLO
            # configured): objective, per-window burn rates, good/bad
            # split, sketch latency quantiles, and whether the engine is
            # currently shedding on burn; a tenant-owned engine reports
            # its OWN plane (per-tenant isolation), not the global one
            "slo": (self.slo_plane.stats() if self.slo_plane is not None
                    else _slo.stats()),
        }
