"""Shape buckets: the padding contract between wire requests and XLA.

Every distinct (shape, dtype) signature reaching a jitted Predictor is one
XLA compile; a serving process that compiles mid-request stalls the whole
batch lane for seconds. A `ShapeBucket` declares the canonical padded item
shapes and the allowed batch sizes up front so the engine pads every
request onto a small closed set of signatures — warmed at startup, zero
retraces in steady state (reference role: the TensorRT profile /
dynamic-shape bucket declarations of `paddle/fluid/inference/`; same idea
as Triton's preferred_batch_size + ragged-input padding).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ShapeBucket", "BucketSet", "signature_of", "default_batch_sizes"]

# (trailing item shape, dtype name) per model input — batch dim excluded
Signature = Tuple[Tuple[Tuple[int, ...], str], ...]


def signature_of(arrays: Sequence[np.ndarray]) -> Signature:
    """Per-item signature of a request: trailing dims + dtype per input
    (the leading dim is the request's batch and is bucketed separately)."""
    return tuple((tuple(a.shape[1:]), str(a.dtype)) for a in arrays)


def default_batch_sizes(max_batch_size: int) -> Tuple[int, ...]:
    """Powers of two up to max_batch_size (each size is one compile)."""
    sizes = []
    b = 1
    while b < max_batch_size:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch_size)
    return tuple(sizes)


class ShapeBucket:
    """One padded lane: canonical item shapes + the batch-size ladder."""

    def __init__(self, item_shapes: Sequence[Sequence[int]],
                 dtypes: Sequence[str],
                 batch_sizes: Sequence[int],
                 learned: bool = False):
        self.item_shapes: Tuple[Tuple[int, ...], ...] = tuple(
            tuple(int(d) for d in s) for s in item_shapes)
        self.dtypes: Tuple[str, ...] = tuple(str(d) for d in dtypes)
        self.batch_sizes: Tuple[int, ...] = tuple(sorted(set(
            int(b) for b in batch_sizes)))
        if not self.batch_sizes or self.batch_sizes[0] < 1:
            raise ValueError(f"bad batch sizes {batch_sizes}")
        self.learned = learned

    @property
    def max_batch_size(self) -> int:
        return self.batch_sizes[-1]

    @property
    def signature(self) -> Signature:
        return tuple(zip(self.item_shapes, self.dtypes))

    def key(self):
        return self.signature

    def accepts(self, sig: Signature) -> bool:
        """True if a request with `sig` can be padded into this bucket:
        same arity/dtypes/rank, every trailing dim <= the bucket dim."""
        if len(sig) != len(self.item_shapes):
            return False
        for (shape, dt), bshape, bdt in zip(sig, self.item_shapes,
                                            self.dtypes):
            if dt != bdt or len(shape) != len(bshape):
                return False
            if any(d > bd for d, bd in zip(shape, bshape)):
                return False
        return True

    def padding_cost(self, sig: Signature) -> int:
        """Padded elements per item when `sig` rides this bucket — the
        resolve tie-break (smallest waste wins)."""
        cost = 0
        for (shape, _), bshape in zip(sig, self.item_shapes):
            n = int(np.prod(shape)) if shape else 1
            bn = int(np.prod(bshape)) if bshape else 1
            cost += bn - n
        return cost

    def round_up_batch(self, rows: int) -> int:
        """Smallest declared batch size >= rows."""
        for b in self.batch_sizes:
            if b >= rows:
                return b
        return self.max_batch_size

    def pad_item(self, arr: np.ndarray, slot: int) -> np.ndarray:
        """Zero-pad one request array's trailing dims up to the bucket's
        canonical item shape (leading/batch dim untouched)."""
        target = self.item_shapes[slot]
        if tuple(arr.shape[1:]) == target:
            return arr
        pads = [(0, 0)] + [(0, t - d) for d, t in zip(arr.shape[1:], target)]
        return np.pad(arr, pads)

    def describe(self) -> Dict:
        return {"item_shapes": [list(s) for s in self.item_shapes],
                "dtypes": list(self.dtypes),
                "batch_sizes": list(self.batch_sizes),
                "learned": self.learned}

    def __repr__(self):
        return (f"ShapeBucket(shapes={self.item_shapes}, "
                f"dtypes={self.dtypes}, batch={self.batch_sizes}, "
                f"learned={self.learned})")


class BucketSet:
    """Thread-safe registry of declared (and optionally learned) buckets."""

    def __init__(self, learn: bool = True,
                 default_batch_sizes_: Optional[Sequence[int]] = None):
        self._lock = threading.Lock()
        self._buckets: Dict[Signature, ShapeBucket] = {}
        self._learn = bool(learn)
        self._default_bs = tuple(default_batch_sizes_ or (1,))

    def declare(self, item_shapes, dtypes,
                batch_sizes: Optional[Sequence[int]] = None) -> ShapeBucket:
        b = ShapeBucket(item_shapes, dtypes,
                        batch_sizes or self._default_bs)
        with self._lock:
            self._buckets[b.key()] = b
        return b

    def resolve(self, sig: Signature) -> Optional[ShapeBucket]:
        """Exact-signature bucket, else the accepting bucket with the least
        padding, else (learn mode) a new exact bucket, else None."""
        with self._lock:
            b = self._buckets.get(sig)
            if b is not None:
                return b
            candidates = [bk for bk in self._buckets.values()
                          if bk.accepts(sig)]
        if candidates:
            return min(candidates, key=lambda bk: bk.padding_cost(sig))
        if not self._learn:
            return None
        learned = ShapeBucket([s for s, _ in sig], [d for _, d in sig],
                              self._default_bs, learned=True)
        with self._lock:
            # another submitter may have raced the learn: keep the first
            return self._buckets.setdefault(learned.key(), learned)

    def buckets(self) -> List[ShapeBucket]:
        with self._lock:
            return list(self._buckets.values())

    def __len__(self):
        with self._lock:
            return len(self._buckets)
