"""ASP — automatic structured (n:m) sparsity.

Reference parity: `python/paddle/fluid/contrib/sparsity/asp/asp.py:1`
(`prune_model` computes 2:4 masks per supported weight,
`decorate(optimizer)` re-applies masks after each optimizer step so
training preserves the sparsity pattern; `check_sparsity` validates).

TPU-native: masks are plain arrays multiplied into the weights — XLA fuses
the multiply; the value is the n:m-sparse deployment artifact and the
accuracy protocol (prune -> masked finetune), not a special kernel.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def compute_mask(w: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """n:m mask along the REDUCTION dim (dim 0 of an [in, out] matmul
    weight): in every group of m consecutive inputs, keep the n largest
    |w| per output channel."""
    w = np.asarray(w)
    if w.ndim != 2 or w.shape[0] % m != 0:
        return np.ones_like(w)
    din, dout = w.shape
    g = np.abs(w).reshape(din // m, m, dout)
    # indices of the top-n |w| within each group
    order = np.argsort(-g, axis=1)[:, :n, :]
    mask = np.zeros_like(g)
    np.put_along_axis(mask, order, 1.0, axis=1)
    return mask.reshape(din, dout).astype(w.dtype)


def check_sparsity(w, n: int = 2, m: int = 4) -> bool:
    """True iff every m-group along dim 0 has at most n non-zeros."""
    w = np.asarray(w)
    if w.ndim != 2 or w.shape[0] % m != 0:
        return False
    nz = (w.reshape(w.shape[0] // m, m, w.shape[1]) != 0).sum(axis=1)
    return bool((nz <= n).all())


def prune_model(model, n: int = 2, m: int = 4, min_dim: int = 4):
    """Compute + apply n:m masks to every prunable 2-D weight (reference
    prune_model). Masks are stored ON the model (`model._asp_masks`) so
    their lifetime tracks the model's. Returns {param_name: mask}."""
    masks = {}
    for name, p in model.named_parameters():
        if len(p.shape) != 2 or p.shape[0] % m != 0 or min(p.shape) < min_dim:
            continue
        mask = compute_mask(np.asarray(p._value), n, m)
        p._value = p._value * jnp.asarray(mask)
        masks[name] = jnp.asarray(mask)
    model._asp_masks = masks
    return masks


def decorate(optimizer, model):
    """Wrap optimizer.step so masks are re-applied after every update
    (reference ASP decorate: OptimizerWithSparsityGuarantee)."""
    masks = getattr(model, "_asp_masks", {})
    named = dict(model.named_parameters())
    inner_step = optimizer.step

    def step():
        out = inner_step()
        for name, mask in masks.items():
            p = named[name]
            p._value = p._value * mask
        return out

    optimizer.step = step
    return optimizer
